"""Headline benchmark: GLMix logistic training throughput on one chip.

The HEADLINE workload is the north-star shard (BASELINE.json: 1B-coefficient
GLMix): a single-chip tile of the production (data x feat) grid layout —
2^24 feature-sharded coefficients, 2^20 rows — solved with L-BFGS through
the routed sparse grid engine. Throughput counts example-passes (rows
touched per objective evaluation) per second. It is measured FIRST so a
tunnel failure later in the run cannot cost the round its number.

Riding along in the same JSON line:
- ``wallclock_to_auc_s``: MLPerf-style time-to-accuracy ON THE HEADLINE
  WORKLOAD — seconds of training until held-out AUC is within AUC_MARGIN of
  the converged final AUC of this fixed workload. Unlike passes/sec this
  cannot be gamed by slower-converging configurations.
- ``smalldim_passes_per_s`` + ``engines``: the FE+RE engine A/B at a small
  (131k-dim) fixed-effect shape — ELL vs stage-by-stage Benes vs fused
  permutation kernels vs the Pallas dense RE path.

``vs_baseline`` is the measured speedup against a CPU/numpy implementation
of the identical math (the reference's per-partition Breeze kernels without
any Spark shuffle/broadcast overhead — a deliberately generous stand-in for
the Spark-CPU baseline, which BASELINE.json targets at >=10x). The CPU
baseline per-eval time is PINNED in-repo (BENCH_BASELINE_PIN.json, median
of >=10 reps + host fingerprint) so the ratio cannot swing run-to-run with
host noise; both ``vs_baseline_pinned`` and ``vs_baseline_fresh`` are
reported, and ``vs_baseline`` is the pinned one when a pin exists.

Failure contract: every exit path emits ONE well-formed JSON line. If no
phase completed, the line replays the last good in-repo measurement
(BENCH_LASTGOOD.json) marked ``"stale": true`` — a tunnel outage must
never zero a round whose repo holds a same-day good number.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
``--engine ell|benes|fused`` restricts the small-dim engine A/B;
``BENCH_SMOKE=1`` shrinks every shape for a CPU smoke run (no pin/lastgood
file IO); ``BENCH_BF16=1`` opts the quality-gated bfloat16-payload A/B
back in on hardware (default-off after the r4 verdict: the engines are
latency-bound, so the halved traffic measured slower on both workloads;
smoke always runs it to keep the gate machinery regression-tested).
"""

from __future__ import annotations

import json
import os
import random
import time

import numpy as np

def _env_flag(name: str) -> bool:
    """0/1 env flag; malformed values read as off (never crash the bench)."""
    try:
        return bool(int(os.environ.get(name, "0")))
    except ValueError:
        return False


_SMOKE = _env_flag("BENCH_SMOKE")
_REPO = os.path.dirname(os.path.abspath(__file__))
_PIN_PATH = os.path.join(_REPO, "BENCH_BASELINE_PIN.json")
_LASTGOOD_PATH = os.path.join(_REPO, "BENCH_LASTGOOD.json")

SEED = 0
N_FE = 1 << (12 if _SMOKE else 18)   # fixed-effect rows
K_NNZ = 32          # nonzeros per row
D_FE = 1 << (10 if _SMOKE else 17)   # global feature dim
N_ENT = 256 if _SMOKE else 4096      # random-effect entities
S_ENT = 32          # samples per entity
D_RE = 16           # per-entity projected dim

# North-star grid shard (single-chip tile of the 1B-coef layout)
N_GRID = 1 << (12 if _SMOKE else 20)     # rows
D_GRID = 1 << (12 if _SMOKE else 24)     # feature-sharded coefficients
K_GRID = 16                              # nonzeros per row

AUC_MARGIN = 0.005  # target = converged final AUC - margin (fixed per seed)

BASELINE_REPS = 3 if _SMOKE else 10  # CPU baseline: median of this many


def _host_fingerprint() -> str:
    """Identify the baseline host so a pinned CPU time is never silently
    compared across machines."""
    model = "unknown"
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    return f"{model} x{os.cpu_count()}"


def _build():
    import jax.numpy as jnp

    from photon_ml_tpu.data.random_effect import ReBucket
    from photon_ml_tpu.ops.data import LabeledData
    from photon_ml_tpu.ops.features import DenseFeatures, EllFeatures

    rng = np.random.default_rng(SEED)
    ell_vals = rng.standard_normal((N_FE, K_NNZ)).astype(np.float32)
    ell_idx = rng.integers(0, D_FE, (N_FE, K_NNZ)).astype(np.int32)
    w_true = (rng.standard_normal(D_FE) * 0.1).astype(np.float32)
    z = (ell_vals * w_true[ell_idx]).sum(-1)
    y = (rng.random(N_FE) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)

    fe_data = LabeledData.create(
        EllFeatures(values=jnp.asarray(ell_vals), indices=jnp.asarray(ell_idx), num_cols=D_FE),
        jnp.asarray(y),
    )

    re_x = rng.standard_normal((N_ENT, S_ENT, D_RE)).astype(np.float32)
    re_wtrue = (rng.standard_normal((N_ENT, D_RE)) * 0.3).astype(np.float32)
    re_z = np.einsum("esd,ed->es", re_x, re_wtrue)
    re_y = (rng.random((N_ENT, S_ENT)) < 1.0 / (1.0 + np.exp(-re_z))).astype(np.float32)
    re_bucket = ReBucket(
        X=jnp.asarray(re_x),
        labels=jnp.asarray(re_y),
        offsets=jnp.zeros((N_ENT, S_ENT), dtype=jnp.float32),
        weights=jnp.ones((N_ENT, S_ENT), dtype=jnp.float32),
        sample_pos=jnp.zeros((N_ENT, S_ENT), dtype=jnp.int32),
        proj_indices=jnp.zeros((N_ENT, D_RE), dtype=jnp.int32),
        proj_valid=jnp.ones((N_ENT, D_RE), dtype=bool),
    )
    re_data = LabeledData(
        features=DenseFeatures(matrix=re_bucket.X),
        labels=re_bucket.labels,
        offsets=re_bucket.offsets,
        weights=re_bucket.weights,
        norm=None,
    )
    return (ell_vals, ell_idx, y), fe_data, (re_x, re_y), re_data


def _auc(scores: np.ndarray, labels: np.ndarray) -> float:
    """Rank-sum ROC AUC (ties averaged), vectorized float64 numpy."""
    order = np.argsort(scores, kind="stable")
    s_sorted = scores[order]
    # average rank of each tie group, assigned back per element
    uniq, inv, counts = np.unique(s_sorted, return_inverse=True, return_counts=True)
    ends = np.cumsum(counts).astype(np.float64)       # 1-based end rank per group
    avg = ends - (counts - 1) / 2.0                   # mean of [end-c+1 .. end]
    ranks = np.empty(len(scores), dtype=np.float64)
    ranks[order] = avg[inv]
    pos = labels > 0.5
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    if not n_pos or not n_neg:
        return float("nan")
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


def _f32_objective_value(w, fe_data_f32) -> float:
    """The exact (f32-engine) FE objective at ``w`` — the quality anchor for
    reduced-precision engines: their own reported objective rides the same
    rounded operator, so a systematic payload bias could hide there."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.losses.objective import make_glm_objective
    from photon_ml_tpu.losses.pointwise import LogisticLoss

    objective = make_glm_objective(LogisticLoss)
    return float(
        jax.jit(objective.value)(w, fe_data_f32, jnp.float32(1.0))
    )


def _settle_dispatch(fn) -> None:
    """Run ``fn`` once more and host-fetch its result leaves.

    On the remote backend, jax.block_until_ready can return prematurely on
    the FIRST dispatch after a compile-cache load (measured: 0.2 ms "ready"
    while the execution takes seconds, completing during a later fetch).
    Fetching the warm-up result does NOT clear that state — it is the next
    dispatch whose completion signal is broken — so the barrier must be a
    fresh dispatch force-fetched to host. Call after the compile warm-up,
    before trusting any block_until_ready-based timer.
    """
    import jax

    for x in jax.tree.leaves(fn()):
        np.asarray(x)


# --------------------------------------------------------------------------
# North-star grid workload (the headline).
# --------------------------------------------------------------------------


def _grid_problem():
    """COO triplets + labels + held-out rows for the 2^24-coef chip tile.
    Generated ONCE per process (cached): the TPU build and the CPU baseline
    share the same arrays."""
    global _GRID_PROBLEM
    if _GRID_PROBLEM is not None:
        return _GRID_PROBLEM
    rng = np.random.default_rng(SEED + 1)
    rows = np.repeat(np.arange(N_GRID, dtype=np.int64), K_GRID)
    cols = rng.integers(0, D_GRID, N_GRID * K_GRID).astype(np.int64)
    vals = rng.standard_normal(N_GRID * K_GRID).astype(np.float32)
    # labels from a sparse true model (materializing w_true [D_GRID] is fine:
    # one float per coefficient, same as the solve itself)
    w_true = (rng.standard_normal(D_GRID) * 0.1).astype(np.float32)
    z = (vals * w_true[cols]).reshape(N_GRID, K_GRID).sum(-1)
    y = (rng.random(N_GRID) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)
    # held-out rows from the same generator: the convergence clock's metric
    n_val = N_GRID // 4
    val_cols = rng.integers(0, D_GRID, n_val * K_GRID).astype(np.int64)
    val_vals = rng.standard_normal(n_val * K_GRID).astype(np.float32)
    val_z = (val_vals * w_true[val_cols]).reshape(n_val, K_GRID).sum(-1)
    val_y = (rng.random(n_val) < 1.0 / (1.0 + np.exp(-val_z))).astype(
        np.float32
    )
    _GRID_PROBLEM = (rows, cols, vals, y, (val_cols, val_vals, val_y))
    return _GRID_PROBLEM


_GRID_PROBLEM = None


def _grid_build(engine: str, payload_dtype: str = "float32"):
    """Route the chip tile through parallel/grid_features on a 1x1 mesh and
    wrap it as LabeledData + a jitted warm solver."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.losses.objective import make_glm_objective
    from photon_ml_tpu.losses.pointwise import LogisticLoss
    from photon_ml_tpu.ops.data import LabeledData
    from photon_ml_tpu.opt.config import (
        GlmOptimizationConfiguration,
        OptimizerConfig,
        RegularizationContext,
    )
    from photon_ml_tpu.opt.solve import solve
    from photon_ml_tpu.parallel.grid_features import (
        grid_from_coo,
        grid_mesh,
        shard_vector_data,
        shard_vector_feat,
    )
    from photon_ml_tpu.types import RegularizationType

    rows, cols, vals, y, val = _grid_problem()
    mesh = grid_mesh(1, 1)
    gf = grid_from_coo(
        rows, cols, vals, (N_GRID, D_GRID), mesh, engine=engine,
        plan_cache=_plan_cache_dir(), payload_dtype=payload_dtype,
    )
    y_pad = np.zeros(gf.num_rows, np.float32)
    y_pad[:N_GRID] = y
    wt_pad = np.zeros(gf.num_rows, np.float32)
    wt_pad[:N_GRID] = 1.0
    data = LabeledData.create(
        gf,
        shard_vector_data(jnp.asarray(y_pad), mesh),
        weights=shard_vector_data(jnp.asarray(wt_pad), mesh),
    )
    objective = make_glm_objective(LogisticLoss)
    cfg = GlmOptimizationConfiguration(
        optimizer_config=OptimizerConfig.lbfgs(max_iterations=10),
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )
    solver = jax.jit(lambda w0, dd: solve(objective, w0, dd, cfg))
    w0 = shard_vector_feat(jnp.zeros(gf.dim, jnp.float32), mesh)
    return solver, w0, data, val


def _grid_headline(engine: str, payload_dtype: str = "float32"):
    """Measure the headline: throughput of an L-BFGS solve over the chip
    tile. Returns (passes/sec, iterations, best solve seconds, final
    objective, (solver, w0, data, val) for the AUC clock)."""
    import jax

    built = _grid_build(engine, payload_dtype)
    solver, w0, data, val = built
    res = solver(w0, data)
    jax.block_until_ready(res.w)  # compile warm-up
    _settle_dispatch(lambda: solver(w0, data).w)
    best = np.inf
    for _ in range(2):
        t0 = time.perf_counter()
        res = solver(w0, data)
        jax.block_until_ready(res.w)
        best = min(best, time.perf_counter() - t0)
    iters = max(int(res.iterations), 1)
    return N_GRID * iters / best, iters, best, float(res.value), built


def _grid_auc_clock(built):
    """Time-to-accuracy ON THE HEADLINE WORKLOAD: warm-started L-BFGS
    passes over the 2^24-coef tile; report the first training-elapsed time
    at which held-out AUC is within AUC_MARGIN of the converged final AUC.
    The workload and margin are fixed by the bench, so a slower-converging
    configuration cannot score better by iterating less."""
    import jax

    solver, w0, data, (val_cols, val_vals, val_y) = built
    w = w0
    # the compile is already warm from the headline measurement
    trace = []  # (training elapsed_s, auc) per pass
    trained = 0.0  # training-only clock: host-side AUC evaluation excluded
    for _ in range(8):  # warm-started passes, to convergence
        t0 = time.perf_counter()
        res = solver(w, data)
        w = res.w
        jax.block_until_ready(w)
        trained += time.perf_counter() - t0
        wf = np.asarray(w)[:D_GRID]
        scores = (val_vals * wf[val_cols]).reshape(-1, K_GRID).sum(-1)
        auc = _auc(scores, val_y)
        trace.append((trained, auc))
        if len(trace) >= 2 and abs(trace[-1][1] - trace[-2][1]) < 1e-4:
            break  # converged
    final = max(a for _, a in trace)
    target = final - AUC_MARGIN
    secs = next(t for t, a in trace if a >= target)
    return secs, target, final, trace


# --------------------------------------------------------------------------
# CPU baselines (the reference's per-partition Breeze kernels in numpy,
# zero communication cost) — pinned in-repo so the ratio is stable.
# --------------------------------------------------------------------------


def _median_time(fn, reps: int) -> float:
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def _cpu_ell_eval_time(ell_vals, ell_idx, y, dim: int) -> float:
    """Median seconds per CPU logistic value+grad evaluation over an ELL
    layout — the one kernel both baselines share (a fix to the baseline
    math must hit the grid and small-dim ratios together)."""
    w = np.zeros(dim, dtype=np.float32)

    def eval_once():
        z = (ell_vals * w[ell_idx]).sum(-1)
        p = 1.0 / (1.0 + np.exp(-z))
        c = (p - y).astype(np.float32)
        g = np.zeros(dim, dtype=np.float32)
        np.add.at(g, ell_idx.ravel(), (ell_vals * c[:, None]).ravel())
        return g

    eval_once()  # page in
    return _median_time(eval_once, BASELINE_REPS)


def _cpu_grid_eval_time() -> float:
    """CPU objective evaluation of the headline grid workload — identical
    math to the TPU solve."""
    rows, cols, vals, y, _ = _grid_problem()
    return _cpu_ell_eval_time(
        vals.reshape(N_GRID, K_GRID), cols.reshape(N_GRID, K_GRID), y, D_GRID
    )


def _cpu_smalldim_eval_times(fe_np, re_np):
    """Median seconds per CPU objective evaluation for the small-dim FE
    problem and the batched RE problem."""
    ell_vals, ell_idx, y = fe_np
    fe_time = _cpu_ell_eval_time(ell_vals, ell_idx, y, D_FE)

    re_x, re_y = re_np
    wr = np.zeros((N_ENT, D_RE), dtype=np.float32)

    def re_eval():
        z = np.einsum("esd,ed->es", re_x, wr)
        p = 1.0 / (1.0 + np.exp(-z))
        c = p - re_y
        return np.einsum("esd,es->ed", re_x, c)

    re_eval()
    return fe_time, _median_time(re_eval, BASELINE_REPS)


def _load_pin() -> dict:
    if _SMOKE:
        return {}
    try:
        with open(_PIN_PATH) as f:
            return json.load(f)
    except Exception:
        return {}


def _maybe_write_pin(pin: dict, fresh: dict) -> dict:
    """First run on a host pins the fresh values; later runs on the SAME
    host keep existing pins (that is the point — a stable denominator) and
    only fill in workloads not pinned yet. A pin from a DIFFERENT host is
    replaced wholesale — cross-host times are not comparable."""
    if _SMOKE:
        return dict(fresh)
    host = _host_fingerprint()
    if pin.get("host") == host:
        missing = {k: v for k, v in fresh.items() if k not in pin}
        if not missing:
            return pin
        new_pin = dict(pin, **missing)
    else:
        new_pin = dict(fresh, host=host, reps=BASELINE_REPS)
    new_pin["measured_at_unix"] = round(time.time(), 1)
    try:
        with open(_PIN_PATH, "w") as f:
            json.dump(new_pin, f, indent=1)
    except OSError:
        pass
    return new_pin


# --------------------------------------------------------------------------
# Small-dim engine A/B (rides along as extras).
# --------------------------------------------------------------------------


def _plan_cache_dir():
    """Routing-plan cache location: BENCH_PLAN_CACHE when set ("" disables),
    else None = the library's safe per-uid default (sparse_perm
    default_plan_cache), shared with the CLIs across runs."""
    return os.environ.get("BENCH_PLAN_CACHE")


def _routed_fe_data(fe_np, engine: str):
    """The same fixed-effect problem through a permutation-routed sparse
    engine: ``"benes"`` = stage-by-stage (ops/sparse_perm.py), ``"fused"`` =
    2m+1 fused kernels per linear map (ops/fused_perm.py), ``"fused_bf16"``
    = fused with bfloat16 network payload (half the stage traffic; entry
    rounding only). The one-time host routing prep is excluded from the
    timed region, like the reference's RDD dataset build; plans are
    pattern-keyed and cached across runs."""
    import functools

    import jax.numpy as jnp

    from photon_ml_tpu.ops.data import LabeledData
    from photon_ml_tpu.ops import fused_perm, sparse_perm

    ell_vals, ell_idx, y = fe_np
    rows = np.repeat(np.arange(N_FE, dtype=np.int64), K_NNZ)
    builder = {
        "benes": sparse_perm.from_coo,
        "fused": fused_perm.from_coo,
        "fused_bf16": functools.partial(
            fused_perm.from_coo, payload_dtype="bfloat16"
        ),
    }[engine]
    feats = builder(rows, ell_idx.ravel().astype(np.int64), ell_vals.ravel(),
                    (N_FE, D_FE), plan_cache=_plan_cache_dir())
    return LabeledData.create(feats, jnp.asarray(y))


def _tpu_run(fe_data, re_data, use_pallas: bool = False):
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.losses.objective import make_glm_objective
    from photon_ml_tpu.losses.pointwise import LogisticLoss
    from photon_ml_tpu.opt.config import GlmOptimizationConfiguration, OptimizerConfig
    from photon_ml_tpu.opt.solve import solve

    objective = make_glm_objective(LogisticLoss, use_pallas=use_pallas)
    cfg = GlmOptimizationConfiguration(
        optimizer_config=OptimizerConfig.lbfgs(max_iterations=50),
        regularization_weight=1.0,
    )
    l2 = jnp.float32(1.0)

    fe_solver = jax.jit(lambda w0, dd: solve(objective, w0, dd, cfg, l2_weight=l2))
    re_solver = jax.jit(
        jax.vmap(lambda w0, dd: solve(objective, w0, dd, cfg, l2_weight=l2), in_axes=(0, 0))
    )
    w0_fe = jnp.zeros((D_FE,), dtype=jnp.float32)
    w0_re = jnp.zeros((N_ENT, D_RE), dtype=jnp.float32)

    def one_pass():
        fe_res = fe_solver(w0_fe, fe_data)
        re_res = re_solver(w0_re, re_data)
        jax.block_until_ready((fe_res.w, re_res.w))
        return fe_res, re_res

    fe_res, re_res = one_pass()  # compile warm-up
    _settle_dispatch(lambda: [r.w for r in one_pass()])
    best = np.inf
    for _ in range(3):
        t0 = time.perf_counter()
        fe_res, re_res = one_pass()
        best = min(best, time.perf_counter() - t0)

    fe_iters = int(fe_res.iterations)
    re_iters = float(jnp.mean(re_res.iterations))
    # rows touched per objective evaluation x evaluations (1 eval/iter is a
    # lower bound; line-search extras are free upside not counted)
    passes = N_FE * fe_iters + N_ENT * S_ENT * re_iters
    return passes, best, fe_iters, re_iters, fe_res


# Best result measured so far: failure paths emit THIS (with the error
# attached) instead of a zero line when a later phase hangs — a wedged
# tunnel after the headline measurement must not discard it.
_PARTIAL: dict = {}


def _emit_failure(error: str) -> None:
    """The benchmark's machine-read failure contract: one well-formed JSON
    line, then a nonzero exit. Precedence: this session's best partial
    result; else the last good in-repo measurement (marked stale); else
    zeros."""
    import sys

    payload = {
        "metric": "glmix_logistic_train_throughput",
        "value": 0.0,
        "unit": "example_passes/sec/chip",
        "vs_baseline": 0.0,
    }
    try:
        # the watchdog thread may race a main-thread _PARTIAL.update (and
        # nested dicts may be live references); any serialization failure
        # must still produce the zeros line, never a hang
        snap = json.loads(json.dumps(dict(_PARTIAL), default=str))
        payload.update(snap)
    except Exception:
        pass
    if not payload.get("value") and not _SMOKE:
        # nothing measured this session: replay the last good in-repo
        # record, honestly marked stale, rather than zeroing the round
        try:
            with open(_LASTGOOD_PATH) as f:
                lastgood = json.load(f)
            if lastgood.get("value"):
                payload = dict(lastgood)
                payload["stale"] = True
        except Exception:
            pass
    payload["error"] = error
    try:
        line = json.dumps(payload)
    except Exception:
        line = json.dumps(
            {"metric": "glmix_logistic_train_throughput", "value": 0.0,
             "unit": "example_passes/sec/chip", "vs_baseline": 0.0,
             "error": error}
        )
    print(line, flush=True)
    sys.stderr.write(f"bench failure: {error}\n")
    os._exit(2 if not payload.get("value") else 3)


_HISTORY_PATH = os.path.join(_REPO, "BENCH_HISTORY.jsonl")


def _append_history(payload: dict, mode: str) -> None:
    """Perf-trajectory sentinel: append the headline numbers of every bench
    artifact to BENCH_HISTORY.jsonl (one compact record per measurement).
    dev-scripts/check_perf_trajectory.py walks this file. Smoke runs skip
    the append (the bench contract: smoke must not touch committed
    artifacts) unless BENCH_HISTORY_WRITE opts in."""
    if _SMOKE and not _env_flag("BENCH_HISTORY_WRITE"):
        return
    rec = {
        "ts": round(time.time(), 1),
        "mode": mode,
        "metric": payload.get("metric"),
        "value": payload.get("value"),
        "unit": payload.get("unit"),
        "host": _host_fingerprint(),
    }
    if payload.get("error"):
        rec["error"] = payload["error"]
    try:
        with open(_HISTORY_PATH, "a") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
    except OSError:
        pass


def _write_lastgood(payload: dict) -> None:
    """Record a successful full measurement in-repo: the stale-fallback
    source for a later run that cannot reach the backend at all."""
    if _SMOKE:
        return
    rec = dict(payload)
    rec["measured_at_unix"] = round(time.time(), 1)
    rec["host"] = _host_fingerprint()
    try:
        with open(_LASTGOOD_PATH, "w") as f:
            json.dump(rec, f, indent=1)
    except OSError:
        pass
    _append_history(rec, "headline")


def _arm_watchdog(seconds: int = 2700) -> None:
    """Hard deadline: if the accelerator backend hangs (e.g. the device
    tunnel is wedged), still emit one well-formed JSON line and exit instead
    of blocking the caller forever."""
    import threading

    t = threading.Timer(
        seconds, lambda: _emit_failure(f"watchdog: no result within {seconds}s")
    )
    t.daemon = True
    t.start()


def _backend_preflight(timeout_s: int = 300, watchdog_s: int = 2700) -> None:
    """Prove the accelerator backend answers at all before building the
    workload: a wedged device tunnel hangs on first use, and failing in
    minutes beats burning the full watchdog budget. Timeouts (a flapping
    tunnel) retry while they fit in 40% of the watchdog budget; a child
    that exits with an error (deterministic breakage) fails immediately
    with its stderr tail."""
    import subprocess
    import sys
    import time as _time

    code = "import jax, jax.numpy as jnp; jax.block_until_ready(jnp.arange(4).sum())"
    budget = max(int(0.4 * watchdog_s), timeout_s)
    attempts = max(1, min(3, (budget + 60) // (timeout_s + 60)))
    last = "unknown"
    for attempt in range(attempts):
        try:
            subprocess.run(
                [sys.executable, "-c", code], timeout=timeout_s,
                check=True, capture_output=True,
            )
            return
        except subprocess.CalledProcessError as e:
            tail = (e.stderr or b"")[-300:].decode("utf-8", "replace").strip()
            _emit_failure(f"backend preflight child failed: {tail or e}")
        except Exception as e:
            last = type(e).__name__
            print(
                f"backend preflight attempt {attempt + 1}/{attempts} "
                f"failed: {last}",
                file=sys.stderr,
            )
            if attempt + 1 < attempts:
                _time.sleep(60)
    _emit_failure(f"backend preflight failed after {attempts} attempts: {last}")


def _bench_telemetry(mode: str = "bench"):
    """Enable span tracing + a run ledger for a sub-bench; return a
    summarizer.

    The summarizer finishes the telemetry run, VALIDATES its own ledger and
    Chrome trace (schema checks from telemetry/validate.py — malformed
    telemetry fails the bench loudly instead of silently shipping a BENCH
    artifact), and returns the compact telemetry block embedded in the
    bench JSON artifact: jit compile/retrace counts, the top-level span
    tree, transfer.* totals, and the validated ledger/trace paths (so
    ``analyze_run`` can replay the bench afterwards). Ledger and trace land
    in $BENCH_TELEMETRY_DIR (default: a fresh temp dir) — never in the
    repo, so smoke runs cannot touch committed artifacts. device_sync
    stays OFF so instrumented barrier requests cannot perturb the measured
    numbers."""
    import tempfile

    from photon_ml_tpu.telemetry import (
        disable_tracing,
        get_registry,
        jit_trace_counts,
        span_tree_summary,
        start_run,
        validate_chrome_trace,
        validate_ledger,
    )

    out_dir = os.environ.get("BENCH_TELEMETRY_DIR") or tempfile.mkdtemp(
        prefix=f"bench-telemetry-{mode}-"
    )
    os.makedirs(out_dir, exist_ok=True)
    ledger_path = os.path.join(out_dir, f"{mode}-ledger.jsonl")
    trace_path = os.path.join(out_dir, f"{mode}-trace.json")
    get_registry().reset()
    run = start_run(
        f"bench-{mode}",
        ledger_path=ledger_path,
        trace_path=trace_path,
        device_sync=False,
    )
    tracer = run.tracer

    def summarize():
        run.finish()
        disable_tracing()
        num_records = len(validate_ledger(ledger_path))
        validate_chrome_trace(trace_path)
        counters = get_registry().snapshot()["counters"]
        transfers = {
            k[len("transfer."):]: v
            for k, v in counters.items()
            if k.startswith("transfer.")
        }
        return {
            "num_spans": len(tracer),
            "jit_traces": jit_trace_counts(),
            "span_tree": span_tree_summary(tracer.spans(), max_depth=2),
            "ledger": ledger_path,
            "trace": trace_path,
            "ledger_records": num_records,
            "validated": True,
            **({"transfers": transfers} if transfers else {}),
        }

    # expose the live run so sub-benches can attach more producers to the
    # same validated ledger (the scenarios bench drains request-plane
    # records into it; validate_ledger in summarize() then schema-checks
    # them like every other record kind)
    summarize.run = run
    return summarize


# ---- online serving benchmark (bench.py --serving) ----

N_SRV_REQ = 400 if _SMOKE else 20_000       # replayed requests
D_SRV_FE = 1 << (8 if _SMOKE else 14)       # fixed-effect dim
N_SRV_ENT = 512 if _SMOKE else 100_000      # RE entities
D_SRV_RE = 16                               # per-entity dim
K_SRV_FE = 16                               # FE nonzeros per request
SRV_SHARDS = 4                              # device shards per RE table
# one scorer replica per serving device: extra replicas on the single CPU
# device only contend on the GIL (multi-replica mode is exercised by the
# CLI and the unit tests, not the throughput bench)
SRV_SCORERS = 1
SRV_BUDGET = 256 if _SMOKE else 16_384      # device-resident rows per coord
SRV_CACHE = 256 if _SMOKE else 4096         # scorer entity-cache capacity
SRV_ADMIT = 64                              # rows per async admission step
SRV_ADMIT_INTERVAL_S = 0.02                 # admission cadence (see below)
SRV_BUCKETS = (1, 4, 16, 64, 256, 512)
SRV_MAX_QUEUE = 512                         # continuous-batching backpressure
SRV_DEADLINE_S = 0.002                      # continuous-batching deadline
# replay passes: pass 1 pulls the deferred tail on-device, later passes
# measure the admitted steady state; the best pass is the headline (the
# shared host is noisy run-to-run) and every pass's numbers are recorded
SRV_REPLAY_REPS = 1 if _SMOKE else 5
# eviction-policy A/B: a tight device budget + entity ids permuted away
# from the packed row order (an UNSORTED artifact — popularity no longer
# aligned with the pinned base prefix), so most of the Zipf mass flows
# through admission headroom and the victim rule decides who stays. The
# admit batch must be well under the headroom (0.25 × budget): waves
# larger than the headroom evict their own cohort and no policy can win
# full scale: ~20k Zipf(1.3) draws touch only a few thousand distinct
# entities, so the budget must sit well under that (headroom well under
# the distinct deferred set) or neither policy ever has to evict
EV_BUDGET = 192 if _SMOKE else 2048
EV_ADMIT = 8 if _SMOKE else 64              # rows per fixed-shape admit step
EV_CHUNK = 128                              # synchronous replay batch rows
# multi-model tenancy arm: N variants on the shared scorer, each a delta
# overlay touching MM_DELTA_ROWS entities, traffic split evenly via the
# variant router across MM_TENANTS
MM_VARIANTS = 4
MM_DELTA_ROWS = 64 if _SMOKE else 512
MM_TENANTS = ("alpha", "beta", "gamma", "delta")
_SERVING_PATH = os.path.join(_REPO, "BENCH_SERVING.json")
_SCENARIOS_PATH = os.path.join(_REPO, "BENCH_SCENARIOS.json")


def _build_serving_workload(seed=None):
    """The synthetic GLMix serving workload shared by ``--serving`` and
    ``--scenarios``: a dense FE prior, one RE coordinate with Zipf(1.3)
    entity popularity (~2% of entities take most traffic), N_SRV_REQ
    sparse requests. Returns (artifact, requests, ent)."""
    from photon_ml_tpu.indexmap import DefaultIndexMap
    from photon_ml_tpu.serving import ServingArtifact, ServingTable
    from photon_ml_tpu.serving.scorer import ScoreRequest
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(SEED if seed is None else seed)
    fe_w = (rng.standard_normal(D_SRV_FE) * 0.1).astype(np.float32)
    re_table = (
        rng.standard_normal((N_SRV_ENT, D_SRV_RE)) * 0.3
    ).astype(np.float32)
    artifact = ServingArtifact(
        task=TaskType.LOGISTIC_REGRESSION,
        tables={
            "fixed": ServingTable(
                feature_shard="global", random_effect_type=None,
                weights=fe_w,
            ),
            "per_user": ServingTable(
                feature_shard="per_user", random_effect_type="userId",
                weights=re_table,
                entity_index=DefaultIndexMap(
                    {f"u{i}": i for i in range(N_SRV_ENT)}
                ),
            ),
        },
        model_name="serving-bench",
    )

    ent = (rng.zipf(1.3, N_SRV_REQ) - 1) % N_SRV_ENT
    fe_idx = rng.integers(0, D_SRV_FE, (N_SRV_REQ, K_SRV_FE))
    fe_val = rng.standard_normal((N_SRV_REQ, K_SRV_FE)).astype(np.float32)
    re_val = rng.standard_normal((N_SRV_REQ, D_SRV_RE)).astype(np.float32)
    requests = [
        ScoreRequest(
            request_id=f"r{i}",
            features={
                "global": {
                    int(c): float(v)
                    for c, v in zip(fe_idx[i], fe_val[i])
                },
                "per_user": {
                    j: float(re_val[i, j]) for j in range(D_SRV_RE)
                },
            },
            entity_ids={"userId": f"u{ent[i]}"},
        )
        for i in range(N_SRV_REQ)
    ]
    return artifact, requests, ent


def _serving_bench():
    """Replay a synthetic GLMix request stream through the serving stack.

    The workload models the production shape: a dense FE prior, one RE
    coordinate with a heavy-tailed (Zipf) entity popularity, a device row
    budget that leaves the cold tail host-resident (admitted async), and
    requests continuously microbatched into power-of-two buckets scored
    against the sharded device tables. Emits ONE JSON line and writes
    BENCH_SERVING.json; an exception emits an error line instead (never a
    bare traceback — same contract as the training bench)."""
    import sys

    try:
        import jax

        if _SMOKE:
            jax.config.update("jax_platforms", "cpu")
        from photon_ml_tpu.serving import (
            AdmissionController,
            ShardedGameScorer,
            replay_requests,
        )
        from photon_ml_tpu.serving.scorer import ScoreRequest

        summarize_telemetry = _bench_telemetry("serving")
        artifact, requests, ent = _build_serving_workload()

        routing = None
        scorers = []
        for _ in range(SRV_SCORERS):
            s = ShardedGameScorer(
                artifact,
                max_nnz={"global": K_SRV_FE, "per_user": D_SRV_RE},
                num_shards=SRV_SHARDS,
                device_budget_rows=SRV_BUDGET,
                routing=routing,
            )
            routing = s.routing
            scorers.append(s)
        lead = scorers[0]
        # warmup: compile every bucket on every replica once outside the
        # timed replay (steady-state latency is the serving number; cold
        # compiles are a deploy-time cost), then drop the warmup's routing
        # accounting
        for s in scorers:
            for b in SRV_BUCKETS:
                s.score_batch(requests[:b], bucket_size=b)
        warm_compiles = max(s.compile_count for s in scorers)
        lead.routing.reset_counters()
        # admission attaches after warmup so its counters only see the
        # timed replay; warmup() compiles its fixed-shape scatter now so
        # the first real admit never compiles under live traffic
        admission = AdmissionController(scorers, admit_batch=SRV_ADMIT)
        for s in scorers:
            s.attach_admission(admission)
        admission.warmup()
        # pre-start admission at a measured cadence (replay would start it
        # at a 1ms default): small donated-scatter steps every 20ms admit
        # the whole deferred tail during the replay without the step's
        # GIL-held bookkeeping showing up as request-latency spikes
        admission.start(interval_s=SRV_ADMIT_INTERVAL_S)
        # serving processes pin or disable the cyclic collector; with it
        # enabled, gen-2 sweeps of the request/handle graph land in p99
        import gc

        reps = []
        gc.collect()
        gc.disable()
        try:
            for _ in range(SRV_REPLAY_REPS):
                _, snapshot = replay_requests(
                    scorers, requests, bucket_sizes=SRV_BUCKETS,
                    model_id="serving-bench",
                    continuous=True,
                    max_wait_s=SRV_DEADLINE_S,
                    max_queue=SRV_MAX_QUEUE,
                    admission=admission,
                )
                reps.append(snapshot)
        finally:
            gc.enable()
            admission.stop()
        snapshot = max(reps, key=lambda s: s.get("replay_requests_per_s", 0.0))

        # --- eviction-policy A/B: oldest (FIFO) vs importance (freq × norm)
        # victim selection at an admission-bound budget. The replay is
        # synchronous (score chunk → admission steps) so both arms see an
        # IDENTICAL request/admission interleaving; the only degree of
        # freedom is who gets evicted. Headline: post-warmup
        # device_resident_rate at equal device_budget_rows.
        perm = np.random.default_rng(SEED + 3).permutation(N_SRV_ENT)
        ab_requests = [
            ScoreRequest(
                request_id=f"e{i}",
                features=requests[i].features,
                entity_ids={"userId": f"u{perm[ent[i]]}"},
            )
            for i in range(N_SRV_REQ)
        ]

        def _eviction_arm(policy, score_delta=True):
            s = ShardedGameScorer(
                artifact,
                max_nnz={"global": K_SRV_FE, "per_user": D_SRV_RE},
                num_shards=SRV_SHARDS,
                device_budget_rows=EV_BUDGET,
                eviction_policy=policy,
                score_delta=score_delta,
            )
            adm = AdmissionController([s], admit_batch=EV_ADMIT)
            s.attach_admission(adm)
            adm.warmup()
            routing = s.routing["per_user"]

            def _pass():
                for lo in range(0, len(ab_requests), EV_CHUNK):
                    s.score_batch(
                        ab_requests[lo:lo + EV_CHUNK], bucket_size=EV_CHUNK
                    )
                    # a couple of fixed-shape admit steps per chunk: the
                    # cadence the async thread sustains, made deterministic
                    adm.step()
                    adm.step()

            _pass()  # warmup: residency + the frequency plane fill in
            warm_c = s.compile_count
            routing.reset_counters()
            _pass()  # measured
            st = routing.stats()
            total = max(1, int(st["total_lookups"]))
            arm = {
                "device_resident_rate": round(
                    st["resident_lookups"] / total, 4
                ),
                "deferred_rate": round(st["deferred_lookups"] / total, 4),
                "evicted_total": int(st["evicted_total"]),
                "admitted_total": int(st["admitted_total"]),
                "post_warmup_compiles": s.compile_count - warm_c,
            }
            if policy == "importance":
                arm["importance_mean"] = round(st["importance_mean"], 4)
                arm["importance_max"] = round(st["importance_max"], 4)
            return arm

        eviction_ab = {
            "device_budget_rows": EV_BUDGET,
            "chunk_rows": EV_CHUNK,
            "oldest": _eviction_arm("oldest"),
            "importance": _eviction_arm("importance"),
            # third arm: importance WITHOUT the |score - fe_only| EWMA
            # fold-in — isolates what the score-delta signal itself buys
            # over plain frequency x norm at the same budget
            "importance_no_delta": _eviction_arm(
                "importance", score_delta=False
            ),
        }
        eviction_ab["resident_rate_gain"] = round(
            eviction_ab["importance"]["device_resident_rate"]
            - eviction_ab["oldest"]["device_resident_rate"], 4
        )
        eviction_ab["score_delta_gain"] = round(
            eviction_ab["importance"]["device_resident_rate"]
            - eviction_ab["importance_no_delta"]["device_resident_rate"], 4
        )

        # --- multi-model tenancy arm: MM_VARIANTS variants (shared FE
        # base + per-variant delta overlays) vs ONE model, both served
        # through the SAME tenancy-plane machinery over the same warm
        # scorers and the same seeded-shuffled arrival stream, reps
        # interleaved arm over arm. Pinning everything but the variant
        # count isolates what N variants actually cost — routing hash,
        # per-variant batchers, overlay index probes — from constants
        # both arms pay anyway (plane bookkeeping, CPU clock drift, and
        # the memory-locality bonus a sequential unshuffled replay would
        # hand whichever arm keeps the request list contiguous; arrival
        # order in production has no such layout locality). The plain
        # sealed path is reported alongside as a reference point.
        # Acceptance: throughput_ratio >= 0.9 at 4 variants.
        from photon_ml_tpu.incremental import build_delta
        from photon_ml_tpu.serving import (
            ServingMetrics,
            TenancyPlane,
            VariantRegistry,
            VariantRouter,
        )
        from photon_ml_tpu.serving.tenancy import tag_request

        registry = VariantRegistry(scorers)
        vrng = np.random.default_rng(SEED + 11)
        variant_ids = ["base"]
        for vi in range(1, MM_VARIANTS):
            vid = f"v{vi}"
            registry.add_variant(vid)
            picks = vrng.choice(N_SRV_ENT, size=MM_DELTA_ROWS, replace=False)
            re_updates = {
                "per_user": {
                    f"u{e}": {
                        int(j): float(x)
                        for j, x in zip(
                            vrng.integers(0, D_SRV_RE, 4),
                            vrng.normal(0.0, 0.05, 4),
                        )
                    }
                    for e in picks
                }
            }
            registry.apply_delta(
                vid, build_delta(re_updates, artifact, generation=1)
            )
            variant_ids.append(vid)
        router = VariantRouter(seed=SEED)
        for vid in variant_ids[1:]:
            router.set_ramp(vid, 100.0 / MM_VARIANTS)
        multi_plane = TenancyPlane(
            registry,
            router=router,
            metrics=ServingMetrics(),
            bucket_sizes=SRV_BUCKETS,
            max_wait_s=SRV_DEADLINE_S,
        )
        single_plane = TenancyPlane(
            registry,
            router=VariantRouter(seed=SEED),
            metrics=ServingMetrics(),
            bucket_sizes=SRV_BUCKETS,
            max_wait_s=SRV_DEADLINE_S,
        )
        stream = [
            tag_request(req, MM_TENANTS[i % len(MM_TENANTS)])
            for i, req in enumerate(requests)
        ]
        random.Random(SEED + 23).shuffle(stream)
        # warm both arms' paths; the measured replays drain on full
        # buckets only (poll_every=0) — sealed policy, equal batch shapes
        single_plane.replay(stream[: SRV_BUCKETS[-1]], poll_every=0)
        multi_plane.replay(stream[: SRV_BUCKETS[-1]], poll_every=0)
        single_rps, multi_rps, sealed_rps = [], [], []

        def _timed_replay(plane):
            t0 = time.perf_counter()
            out = plane.replay(stream, poll_every=0)
            wall = time.perf_counter() - t0
            if len(out) != len(stream):
                raise RuntimeError(
                    f"tenancy replay dropped requests: {len(out)} of "
                    f"{len(stream)}"
                )
            return len(out) / wall if wall > 0 else 0.0

        gc.collect()
        gc.disable()
        try:
            for _ in range(SRV_REPLAY_REPS):
                single_rps.append(_timed_replay(single_plane))
                multi_rps.append(_timed_replay(multi_plane))
                _, snap = replay_requests(
                    scorers, requests, bucket_sizes=SRV_BUCKETS,
                    metrics=ServingMetrics(), model_id="serving-bench",
                    continuous=False,
                )
                sealed_rps.append(snap.get("replay_requests_per_s", 0.0))
        finally:
            gc.enable()
        best_single = max(single_rps)
        best_multi = max(multi_rps)
        multimodel = {
            "num_variants": MM_VARIANTS,
            "tenants": list(MM_TENANTS),
            "delta_rows_per_variant": MM_DELTA_ROWS,
            "serving_mode": "sealed-microbatch",
            "variant_shares": {
                v: round(s, 4) for v, s in router.shares().items()
            },
            "variants": registry.stats(),
            "single_model_requests_per_s": round(best_single, 1),
            "multimodel_requests_per_s": round(best_multi, 1),
            "sealed_reference_requests_per_s": round(max(sealed_rps), 1),
            "rep_single_requests_per_s": [round(r, 1) for r in single_rps],
            "rep_multi_requests_per_s": [round(r, 1) for r in multi_rps],
            "rep_sealed_requests_per_s": [round(r, 1) for r in sealed_rps],
            "throughput_ratio": round(
                best_multi / best_single, 4
            ) if best_single > 0 else 0.0,
        }

        payload = {
            "metric": "serving_p99_latency_s",
            "value": snapshot.get("latency_p99_s", 0.0),
            "unit": "seconds",
            "requests_per_s": snapshot.get("replay_requests_per_s", 0.0),
            "num_requests": N_SRV_REQ,
            "n_entities": N_SRV_ENT,
            "serving_mode": "sharded-continuous",
            "num_scorers": SRV_SCORERS,
            "num_shards": SRV_SHARDS,
            "device_budget_rows": SRV_BUDGET,
            "admit_batch": SRV_ADMIT,
            "admit_interval_ms": SRV_ADMIT_INTERVAL_S * 1e3,
            "batch_deadline_ms": SRV_DEADLINE_S * 1e3,
            "max_queue": SRV_MAX_QUEUE,
            "bucket_sizes": list(SRV_BUCKETS),
            "replay_reps": SRV_REPLAY_REPS,
            "rep_requests_per_s": [
                round(s.get("replay_requests_per_s", 0.0), 1) for s in reps
            ],
            "rep_latency_p99_ms": [
                round(s.get("latency_p99_s", 0.0) * 1e3, 3) for s in reps
            ],
            "warm_compiles": warm_compiles,
            "post_replay_compiles": max(s.compile_count for s in scorers),
            "post_warmup_compiles": (
                max(s.compile_count for s in scorers) - warm_compiles
            ),
            "eviction_ab": eviction_ab,
            "multimodel": multimodel,
            "backend": jax.default_backend(),
            **{
                k: snapshot[k]
                for k in (
                    "latency_p50_s", "latency_p95_s", "latency_p99_s",
                    "batch_fill_ratio", "device_resident_rate",
                    "deferred_rate", "replay_requests_per_s",
                    "per_bucket_latency", "residency", "admission",
                )
                if k in snapshot
            },
        }
        payload["telemetry"] = summarize_telemetry()
        print(json.dumps(payload))
        if not _SMOKE or _env_flag("BENCH_SERVING_WRITE"):
            with open(_SERVING_PATH, "w") as f:
                json.dump(payload, f, indent=2)
        _append_history(payload, "serving")
        _append_history(
            {
                "metric": "eviction_resident_rate_gain",
                "value": eviction_ab["resident_rate_gain"],
                "unit": "importance_minus_oldest_resident_rate",
            },
            "serving_eviction",
        )
        _append_history(
            {
                "metric": "multimodel_throughput_ratio",
                "value": multimodel["throughput_ratio"],
                "unit": f"{MM_VARIANTS}_variant_vs_single_model_rps",
            },
            "serving_multimodel",
        )
    except Exception as e:  # noqa: BLE001 - one JSON line per exit path
        print(json.dumps({
            "metric": "serving_p99_latency_s",
            "error": f"{type(e).__name__}: {e}",
        }))
        sys.exit(1)


# ---- scenario replay harness (bench.py --scenarios) ----

# scenario shaping: phases per scenario and the idle-gap scale (diurnal
# troughs, storm quiets); the request-plane sample rate trades record
# volume for attribution resolution (1 = every request in smoke)
SCN_PHASES = 8
SCN_PAUSE_S = 0.002 if _SMOKE else 0.02
SCN_SAMPLE_RATE = 1 if _SMOKE else 4
SCN_SLO_LATENCY_S = 0.050                   # per-request latency objective
SCN_SLO_LATENCY_OBJ = 0.99
SCN_SLO_AVAIL_OBJ = 0.999
SCN_NEARLINE_ROWS = 8 if _SMOKE else 64     # rows per nearline delta
# emit cadence: the trainer thread's host work (delta build + fingerprint
# + publish) contends on the GIL with the replay thread, so every tick
# inflates the host stages (featurize/dispatch) for requests in flight.
# Production runs the trainer out of process; in this single-process
# bench the cadence is the lever that keeps swap-window tail inflation
# bounded instead of continuous.
SCN_NEARLINE_INTERVAL_S = 0.02 if _SMOKE else 0.2


def _scenarios_bench():
    """Replay the serving workload through the seeded traffic-shape
    scenarios (steady, diurnal, burst storm, cold-entity flood, hot-swap
    under load) with the request plane sampling lifecycles and the SLO
    tracker keeping verdicts.

    One JSON line out; writes BENCH_SCENARIOS.json (full runs, or smoke
    with BENCH_SCENARIOS_WRITE=1) with one document per scenario: per-stage
    p50/p99 breakdown, device residency, throughput, SLO verdict. The
    request records drain into the bench telemetry ledger, so the
    summarizer's validate_ledger schema-checks them — the CI scenario
    sentinel runs this in smoke mode and gates on both artifacts."""
    import shutil
    import sys
    import tempfile

    try:
        import jax

        if _SMOKE:
            jax.config.update("jax_platforms", "cpu")
        from photon_ml_tpu.serving import (
            AdmissionController,
            DEFAULT_TENANTS,
            OverloadController,
            RequestPlane,
            SCENARIO_NAMES,
            SLOTracker,
            ServingMetrics,
            ShardedGameScorer,
            TENANCY_SCENARIOS,
            TenancyPlane,
            TenantBudget,
            TenantQuota,
            VariantRegistry,
            VariantRouter,
            build_scenario,
            build_tenant_slos,
            make_nearline_fn,
            run_scenario,
        )
        from photon_ml_tpu.serving.scenarios import make_row_swap_fn

        summarize_telemetry = _bench_telemetry("scenarios")
        ledger = summarize_telemetry.run.ledger
        artifact, requests, _ = _build_serving_workload()

        routing = None
        scorers = []
        for _ in range(SRV_SCORERS):
            s = ShardedGameScorer(
                artifact,
                max_nnz={"global": K_SRV_FE, "per_user": D_SRV_RE},
                num_shards=SRV_SHARDS,
                device_budget_rows=SRV_BUDGET,
                routing=routing,
            )
            routing = s.routing
            scorers.append(s)
        lead = scorers[0]
        # compile every bucket once outside the measured scenarios (the
        # same deploy-time-cost discipline as the serving bench)
        for s in scorers:
            for b in SRV_BUCKETS:
                s.score_batch(requests[:b], bucket_size=b)
        admission = AdmissionController(scorers, admit_batch=SRV_ADMIT)
        for s in scorers:
            s.attach_admission(admission)
        admission.warmup()
        admission.start(interval_s=SRV_ADMIT_INTERVAL_S)

        # one variant registry shared by the tenancy scenarios (the
        # production regime: the candidate variant accumulates nearline
        # generations across scenarios, on the same warm scorers). Every
        # nearline delta-apply swaps through a validation gate: a held-out
        # replay slice scored per variant, with automatic single-variant
        # rollback on AUC regression. Labels are the base scorer's own
        # top-half ranking, so the base AUC is 1.0 by construction and the
        # gate measures pure ranking drift of the candidate.
        from photon_ml_tpu.serving import ValidationGate

        gate_slice = list(requests[: min(256, len(requests))])
        base_scores = np.asarray(
            [
                r.score
                for r in lead.score_batch(gate_slice, bucket_size=256)
            ],
            dtype=np.float32,
        )
        gate_labels = (base_scores > np.median(base_scores)).astype(
            np.float32
        )
        registry = VariantRegistry(
            scorers,
            gate=ValidationGate(
                gate_slice,
                gate_labels,
                max_auc_regression=0.05,
                bucket_size=256,
            ),
        )
        registry.add_variant("candidate")
        nearline_dir = tempfile.mkdtemp(prefix="bench-nearline-")

        import gc

        scenario_docs = []
        gc.collect()
        gc.disable()
        try:
            for name in SCENARIO_NAMES:
                # scorers/admission stay warm across scenarios (the
                # production regime); verdicts are isolated per scenario
                # via fresh metrics/plane/SLO and reset routing counters
                lead.routing.reset_counters()
                metrics = ServingMetrics()
                slo = SLOTracker(
                    latency_threshold_s=SCN_SLO_LATENCY_S,
                    latency_objective=SCN_SLO_LATENCY_OBJ,
                    availability_objective=SCN_SLO_AVAIL_OBJ,
                )
                tenant_slos = (
                    build_tenant_slos(
                        DEFAULT_TENANTS,
                        latency_threshold_s=SCN_SLO_LATENCY_S,
                        latency_objective=SCN_SLO_LATENCY_OBJ,
                        availability_objective=SCN_SLO_AVAIL_OBJ,
                    )
                    if name in TENANCY_SCENARIOS
                    else None
                )
                plane = RequestPlane(
                    sample_rate=SCN_SAMPLE_RATE,
                    seed=SEED,
                    ledger=ledger,
                    capacity=max(4096, len(requests)),
                    slo=slo,
                    tenant_slos=tenant_slos,
                )
                scenario = build_scenario(
                    name, requests, seed=SEED,
                    num_phases=SCN_PHASES, pause_s=SCN_PAUSE_S,
                    tenants=DEFAULT_TENANTS,
                )
                swap_fn = None
                if name == "hot_swap_under_load":
                    swap_fn = make_row_swap_fn(
                        scorers, metrics, seed=SEED
                    )
                overload = None
                if name not in TENANCY_SCENARIOS:
                    # closed-loop overload control on the plain replay
                    # path: burn-rate >= 1 shrinks batch deadlines and
                    # sheds FE-only-able load until the budget refills
                    overload = OverloadController(slo)
                    overload.attach_scorer(lead)
                tenancy = None
                nearline_fn = None
                if name in TENANCY_SCENARIOS:
                    quota = None
                    if name == "tenant_isolation":
                        # budgets are denominated in each tenant's TOTAL
                        # offered volume, burst-dominated: replay wall
                        # time is whatever the host gives us, so a
                        # per-second rate would make shedding a function
                        # of CPU speed. With 1.25x headroom over the fair
                        # total, non-flooding tenants never touch their
                        # cap while the flooder (FLOOD_FACTOR extra
                        # copies over the mid phases, ~2x fair) must shed.
                        fair_total = max(
                            1, N_SRV_REQ // len(DEFAULT_TENANTS)
                        )
                        quota = TenantQuota({
                            t: TenantBudget(
                                rate=max(1.0, 0.05 * fair_total),
                                burst=max(2, int(1.25 * fair_total)),
                            )
                            for t in DEFAULT_TENANTS
                        })
                    router = VariantRouter(seed=SEED)
                    if name == "nearline_loop":
                        # the nearline-trained candidate takes half the
                        # traffic while its deltas land
                        router.set_ramp("candidate", 50.0)
                        nearline_fn = make_nearline_fn(
                            registry,
                            ["candidate"],
                            {"per_user": [
                                f"u{i}"
                                for i in range(min(N_SRV_ENT, 4096))
                            ]},
                            rows_per_delta=SCN_NEARLINE_ROWS,
                            seed=SEED,
                            watch_dir=nearline_dir,
                        )
                        # warm tick OUTSIDE the measured window: the
                        # first apply compiles the row-update scatter
                        # for this delta shape — a one-time stall that
                        # would otherwise land on one mid-phase bucket
                        # and torch every tenant's 50 ms latency budget
                        nearline_fn()
                    tenancy = TenancyPlane(
                        registry,
                        router=router,
                        plane=plane,
                        quota=quota,
                        metrics=metrics,
                        bucket_sizes=SRV_BUCKETS,
                        max_wait_s=SRV_DEADLINE_S,
                    )
                doc = run_scenario(
                    scenario,
                    scorers,
                    bucket_sizes=SRV_BUCKETS,
                    metrics=metrics,
                    plane=plane,
                    slo=slo,
                    admission=admission,
                    continuous=True,
                    max_wait_s=SRV_DEADLINE_S,
                    max_queue=SRV_MAX_QUEUE,
                    swap_fn=swap_fn,
                    tenancy=tenancy,
                    nearline_fn=nearline_fn,
                    nearline_interval_s=SCN_NEARLINE_INTERVAL_S,
                    overload=overload,
                )
                scenario_docs.append(doc)
        finally:
            gc.enable()
            admission.stop()
            shutil.rmtree(nearline_dir, ignore_errors=True)

        ok = sum(
            1 for d in scenario_docs if d.get("slo_verdict") == "ok"
        )
        payload = {
            "metric": "scenario_slo_ok_rate",
            "value": round(ok / len(scenario_docs), 4),
            "unit": "fraction_of_scenarios",
            "num_scenarios": len(scenario_docs),
            "num_requests_per_scenario": N_SRV_REQ,
            "sample_rate": SCN_SAMPLE_RATE,
            "slo": {
                "latency_threshold_s": SCN_SLO_LATENCY_S,
                "latency_objective": SCN_SLO_LATENCY_OBJ,
                "availability_objective": SCN_SLO_AVAIL_OBJ,
            },
            "serving_mode": "sharded-continuous",
            "num_shards": SRV_SHARDS,
            "device_budget_rows": SRV_BUDGET,
            "bucket_sizes": list(SRV_BUCKETS),
            "tenants": list(DEFAULT_TENANTS),
            "tenancy_scenarios": list(TENANCY_SCENARIOS),
            "backend": jax.default_backend(),
            "scenarios": scenario_docs,
        }
        iso = next(
            (
                d for d in scenario_docs
                if d.get("name") == "tenant_isolation"
            ),
            None,
        )
        if iso is not None:
            payload["tenant_isolation_ok"] = bool(iso.get("isolation_ok"))
        payload["telemetry"] = summarize_telemetry()
        print(json.dumps(payload))
        if not _SMOKE or _env_flag("BENCH_SCENARIOS_WRITE"):
            with open(_SCENARIOS_PATH, "w") as f:
                json.dump(payload, f, indent=2)
        _append_history(payload, "scenarios")
    except Exception as e:  # noqa: BLE001 - one JSON line per exit path
        print(json.dumps({
            "metric": "scenario_slo_ok_rate",
            "error": f"{type(e).__name__}: {e}",
        }))
        sys.exit(1)


N_INC_ENT = 32 if _SMOKE else 1024          # random-effect entities
N_INC_ROWS = 8 if _SMOKE else 40            # base rows per entity
N_INC_TOUCH = 8 if _SMOKE else 128          # entities touched by the update
N_INC_NEW = 4 if _SMOKE else 32             # brand-new entities in the update
D_INC_FE = 16 if _SMOKE else 128            # global feature dim
D_INC_RE = 8                                # per-entity dim
_INCREMENTAL_PATH = os.path.join(_REPO, "BENCH_INCREMENTAL.json")


def _incremental_bench():
    """Time the nearline loop: warm-started incremental re-solve of the
    touched entities, delta publish (atomic dir write + fingerprint) and
    hot-swap into a live scorer (in-place device-table mutation, no
    re-jit). The headline is the incremental update latency — the
    freshness floor of the nearline pipeline; blackout and added compiles
    are the serving-side costs. Emits ONE JSON line and writes
    BENCH_INCREMENTAL.json; an exception emits an error line instead."""
    import sys
    import tempfile
    import time as _time

    try:
        import jax

        if _SMOKE:
            jax.config.update("jax_platforms", "cpu")
        from photon_ml_tpu.data import RandomEffectDataConfiguration
        from photon_ml_tpu.data.game_data import FeatureShard, GameData
        from photon_ml_tpu.estimators.game import (
            FixedEffectCoordinateConfiguration,
            GameEstimator,
            RandomEffectCoordinateConfiguration,
        )
        from photon_ml_tpu.incremental import (
            build_delta,
            delta_dir_name,
            incremental_update,
            save_delta,
        )
        from photon_ml_tpu.opt import (
            GlmOptimizationConfiguration,
            RegularizationContext,
        )
        from photon_ml_tpu.serving import (
            GameScorer,
            HotSwapManager,
            pack_game_model,
        )
        from photon_ml_tpu.serving.replay import (
            max_nnz_of,
            requests_from_game_data,
        )
        from photon_ml_tpu.types import RegularizationType, TaskType

        summarize_telemetry = _bench_telemetry("incremental")
        l2 = lambda lam: GlmOptimizationConfiguration(  # noqa: E731
            regularization=RegularizationContext(RegularizationType.L2),
            regularization_weight=lam,
        )
        rng = np.random.default_rng(SEED)

        def _coo(X):
            r, c = np.nonzero(X)
            return FeatureShard(rows=r, cols=c, vals=X[r, c], dim=X.shape[1])

        def _dataset(entities, rows, wg, wu):
            n = len(entities) * rows
            Xg = rng.normal(size=(n, D_INC_FE)).astype(np.float32)
            Xu = rng.normal(size=(n, D_INC_RE)).astype(np.float32)
            users = np.repeat(entities, rows)
            y = Xg @ wg + np.array(
                [Xu[i] @ wu[users[i]] for i in range(n)], np.float32
            )
            y += 0.05 * rng.normal(size=n).astype(np.float32)
            return GameData(
                labels=y,
                feature_shards={"g": _coo(Xg), "u": _coo(Xu)},
                id_tags={"userId": users},
            )

        wg = rng.normal(size=D_INC_FE).astype(np.float32)
        base_ids = [f"u{i}" for i in range(N_INC_ENT)]
        new_ids = [f"n{i}" for i in range(N_INC_NEW)]
        wu = {
            e: rng.normal(size=D_INC_RE).astype(np.float32)
            for e in base_ids + new_ids
        }
        base_data = _dataset(base_ids, N_INC_ROWS, wg, wu)
        events = _dataset(
            base_ids[:N_INC_TOUCH] + new_ids, max(4, N_INC_ROWS // 2), wg, wu
        )

        estimator = GameEstimator(
            task=TaskType.LINEAR_REGRESSION,
            coordinates={
                "fixed": FixedEffectCoordinateConfiguration("g", l2(0.1)),
                "per_user": RandomEffectCoordinateConfiguration(
                    "u",
                    RandomEffectDataConfiguration(random_effect_type="userId"),
                    l2(1.0),
                ),
            },
            num_outer_iterations=1,
        )
        fit = estimator.fit(base_data)
        artifact = pack_game_model(fit.model, model_name="incremental-bench")

        t0 = _time.perf_counter()
        update = incremental_update(
            estimator, fit.model, events,
            refresh_fixed_iterations=1, merge=False,
        )
        update_s = _time.perf_counter() - t0

        with tempfile.TemporaryDirectory() as tmp:
            t0 = _time.perf_counter()
            delta = build_delta(
                update.re_updates, artifact,
                fe_updates=update.fe_updates or None,
                generation=1, created_at_unix=_time.time(),
            )
            delta_dir = os.path.join(tmp, delta_dir_name(1))
            save_delta(delta, delta_dir)
            publish_s = _time.perf_counter() - t0

            requests = requests_from_game_data(events, artifact)
            scorer = GameScorer(
                artifact, max_nnz=max_nnz_of(requests), growth_headroom=True,
            )
            warm = min(8, len(requests))
            scorer.score_batch(requests[:warm], bucket_size=warm)
            manager = HotSwapManager(scorer)
            report = manager.apply_delta(delta_dir)

        payload = {
            "metric": "incremental_update_latency_s",
            "value": round(update_s, 6),
            "unit": "seconds",
            "publish_s": round(publish_s, 6),
            "swap_blackout_s": round(report.blackout_s, 6),
            "swap_staleness_s": (
                round(report.staleness_s, 6)
                if report.staleness_s is not None else None
            ),
            "swap_compiles_added": report.compiles_added,
            "swap_regrew": list(report.regrew),
            "rows_updated": report.rows_updated,
            "touched_entities": N_INC_TOUCH,
            "new_entities": N_INC_NEW,
            "n_entities": N_INC_ENT,
            "num_events": update.num_events,
            "backend": jax.default_backend(),
            "telemetry": summarize_telemetry(),
        }
        print(json.dumps(payload))
        if not _SMOKE or _env_flag("BENCH_INCREMENTAL_WRITE"):
            with open(_INCREMENTAL_PATH, "w") as f:
                json.dump(payload, f, indent=2)
        _append_history(payload, "incremental")
    except Exception as e:  # noqa: BLE001 - one JSON line per exit path
        print(json.dumps({
            "metric": "incremental_update_latency_s",
            "error": f"{type(e).__name__}: {e}",
        }))
        sys.exit(1)


# --- streaming out-of-core training bench ----------------------------------
N_ST_ROWS = 512 if _SMOKE else 120_000      # training rows
N_ST_VAL = 256 if _SMOKE else 20_000        # held-out rows (in-memory)
D_ST = 24 if _SMOKE else 192                # global feature dim
N_ST_FILES = 3 if _SMOKE else 12            # Avro part files
ST_BLOCK_ROWS = 128 if _SMOKE else 8192     # rows per streamed block
ST_PREFETCH = 2
_STREAMING_PATH = os.path.join(_REPO, "BENCH_STREAMING.json")

# gap-guided scheduling A/B (DuHL): a skewed dataset where only every
# GS_HARD_EVERY-th block carries the real logistic signal; the rest are
# "easy" blocks (near-zero features, constant label) the model fits in one
# bootstrap visit, after which their duality gap collapses. The shuffled
# baseline keeps re-visiting them anyway; the gap scheduler should not.
# Hard blocks are deliberately ill-conditioned — anisotropic feature
# scales with the signal concentrated in the SMALL-scale coordinates — so
# each one-iteration visit makes bounded progress and the trajectory keeps
# rising for many epochs instead of saturating inside the bootstrap pass.
# Per-block shapes REUSE the main streaming fixture (same block_rows, same
# feature dim), and the A/B drives the solver seam directly — never the
# coordinate's row-plane programs, whose static padded-rows argument would
# retrace at this dataset size — so the A/B compiles ZERO new programs
# beyond the stochastic solver family and the all-traces-once contract
# covers both fits and the A/B together.
GS_HARD_EVERY = 4
GS_NUM_BLOCKS = 12 if _SMOKE else 16        # total blocks (1 in 4 hard)
GS_EPOCH_CAP = 10 if _SMOKE else 16         # epochs per arm, both arms
GS_TARGET_FRACTION = 0.95                   # of the shuffle arm's AUC lift
GS_VISIT_FRACTION = 0.25                    # gap arm's scheduled working set
GS_EXPLORE = 0.05                           # stalest-block exploration floor
GS_CHUNK_ITERS = 1                          # solver iters per block visit
N_GS_VAL = 512 if _SMOKE else 8192          # held-out rows (hard distribution)


def _gap_schedule_ab(tmp):
    """Stochastic-mode A/B: gap-guided block scheduling vs the blind
    per-epoch shuffle, measured in BLOCK VISITS to a fixed held-out AUC
    target (DuHL's currency: decode + H2D + solve work all scale with
    visits). Returns the fields merged into the --streaming payload."""
    import jax.numpy as jnp

    from photon_ml_tpu.io.data_reader import (
        FeatureShardConfiguration,
        read_game_data,
        write_training_examples,
    )
    from photon_ml_tpu.opt import (
        GlmOptimizationConfiguration,
        RegularizationContext,
    )
    from photon_ml_tpu.streaming import GapScheduler, StreamingSource
    from photon_ml_tpu.streaming.coordinate import (
        StreamingFixedEffectCoordinate,
        _OwnShardBlocks,
    )
    from photon_ml_tpu.streaming.solver import (
        StreamSolveInfo,
        solve_streaming_stochastic,
    )
    from photon_ml_tpu.types import RegularizationType, TaskType

    rng = np.random.default_rng(SEED + 7)
    # anisotropic scales; signal ∝ 1/scale so small-scale coordinates carry
    # equal AUC weight but converge ~(1/scale)^2 slower under first-order
    # one-iteration visits (fresh solver state per visit — no curvature
    # memory), keeping the trajectory rising across many epochs
    scales = np.logspace(-1.0, 0.0, D_ST).astype(np.float32)
    w_gs = (
        rng.normal(size=D_ST) / scales * (2.0 / np.sqrt(D_ST))
    ).astype(np.float32)
    n_rows = GS_NUM_BLOCKS * ST_BLOCK_ROWS
    num_blocks = GS_NUM_BLOCKS
    # easy blocks: features ~0, label constant — one intercept fit
    X = (rng.normal(size=(n_rows, D_ST)) * 0.01).astype(np.float32)
    y = np.ones(n_rows, dtype=np.float32)
    hard_blocks = []
    for b in range(0, num_blocks, GS_HARD_EVERY):
        hard_blocks.append(b)
        lo = b * ST_BLOCK_ROWS
        hi = min(lo + ST_BLOCK_ROWS, n_rows)
        Xb = (rng.normal(size=(hi - lo, D_ST)) * scales).astype(np.float32)
        X[lo:hi] = Xb
        p = 1.0 / (1.0 + np.exp(-(Xb @ w_gs)))
        y[lo:hi] = (p > rng.random(hi - lo)).astype(np.float32)
    X_va = (rng.normal(size=(N_GS_VAL, D_ST)) * scales).astype(np.float32)
    y_va = (
        1.0 / (1.0 + np.exp(-(X_va @ w_gs))) > rng.random(N_GS_VAL)
    ).astype(np.float32)

    def _records(Xm, ym):
        for i in range(Xm.shape[0]):
            yield {
                "label": float(ym[i]),
                "features": [
                    ("f", str(j), float(Xm[i, j])) for j in range(D_ST)
                ],
            }

    shard_configs = {
        "global": FeatureShardConfiguration(
            feature_bags=("features",), add_intercept=True
        ),
    }
    root = os.path.join(tmp, "gap_ab")
    os.makedirs(root, exist_ok=True)
    # file boundaries on block boundaries (last file takes the remainder)
    # so part-file grouping can deliver its one-decode-per-file guarantee
    blocks_per_file = GS_HARD_EVERY
    paths = []
    fi = 0
    for lo in range(0, n_rows, blocks_per_file * ST_BLOCK_ROWS):
        hi = min(lo + blocks_per_file * ST_BLOCK_ROWS, n_rows)
        p = os.path.join(root, f"part-{fi:05d}.avro")
        write_training_examples(p, _records(X[lo:hi], y[lo:hi]))
        paths.append(p)
        fi += 1
    val_path = os.path.join(root, "val.avro")
    write_training_examples(val_path, _records(X_va, y_va))

    source = StreamingSource.open(
        paths, shard_configs, block_rows=ST_BLOCK_ROWS
    )
    val_data, _, _ = read_game_data(
        [val_path], shard_configs, index_maps=source.index_maps
    )
    sh = val_data.feature_shards["global"]
    v_rows = np.asarray(sh.rows)
    v_cols = np.asarray(sh.cols)
    v_vals = np.asarray(sh.vals)

    def _val_auc(w):
        s = np.zeros(N_GS_VAL, dtype=np.float64)
        np.add.at(s, v_rows, v_vals * w[v_cols])
        return _auc(s, y_va)

    l2 = GlmOptimizationConfiguration(
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=1e-3,
    )
    # the block provider: one coordinate shared by both arms, used ONLY
    # for its shard-restricted streamed pass (no residual fusion — the
    # padded row plane's static shape would retrace at this dataset size)
    coord = StreamingFixedEffectCoordinate(
        source=source,
        shard_id="global",
        task=TaskType.LOGISTIC_REGRESSION,
        configuration=l2,
        prefetch_depth=ST_PREFETCH,
        mode="stochastic",
        epochs=1,
        chunk_iters=GS_CHUNK_ITERS,
        blocks_per_update=1,
        seed=SEED,
    )
    plan = source.plan
    total_weight = float(np.sum(source.row_planes().weights))

    def _arm(gap: bool):
        sched = (
            GapScheduler(
                plan.num_blocks,
                plan=plan,
                visit_fraction=GS_VISIT_FRACTION,
                explore=GS_EXPLORE,
                seed=SEED,
            )
            if gap
            else None
        )
        w = jnp.zeros((coord.dim,), dtype=jnp.float32)
        info = StreamSolveInfo()
        traj = []
        for epoch in range(GS_EPOCH_CAP):
            result = solve_streaming_stochastic(
                coord.objective(),
                w,
                make_blocks_ordered=lambda order: _OwnShardBlocks(
                    coord, None, order
                ),
                configuration=l2,
                num_blocks=plan.num_blocks,
                total_weight=total_weight,
                epochs=1,               # one epoch per call: visit accounting
                chunk_iters=GS_CHUNK_ITERS,
                blocks_per_update=1,
                seed=SEED + epoch,      # fresh shuffle stream every epoch
                info=info,
                scheduler=sched,
            )
            w = result.w
            traj.append(
                (
                    int(info.blocks),
                    round(_val_auc(np.asarray(w, dtype=np.float64)), 6),
                )
            )
        return traj

    shuffle_traj = _arm(False)
    gap_traj = _arm(True)
    best = max(a for _, a in shuffle_traj)
    target = 0.5 + GS_TARGET_FRACTION * (best - 0.5)

    def _to_target(traj):
        # sustained crossing: two consecutive points at/above target (the
        # final point alone qualifies) so a noise-lucky epoch doesn't win
        for i, (v, a) in enumerate(traj):
            if a < target:
                continue
            if i + 1 == len(traj) or traj[i + 1][1] >= target:
                return v, True
        return traj[-1][0], False

    shuffle_visits, shuffle_hit = _to_target(shuffle_traj)
    gap_visits, gap_hit = _to_target(gap_traj)
    return {
        "gap_visits_to_target": gap_visits,
        "shuffle_visits_to_target": shuffle_visits,
        "gap_vs_shuffle_visits": round(
            shuffle_visits / max(gap_visits, 1), 3
        ),
        "gap_schedule_ab": {
            "num_blocks": source.plan.num_blocks,
            "hard_blocks": hard_blocks,
            "target_auc": round(target, 6),
            "target_reached": {"gap": gap_hit, "shuffle": shuffle_hit},
            "visit_fraction": GS_VISIT_FRACTION,
            "explore": GS_EXPLORE,
            "epoch_cap": GS_EPOCH_CAP,
            "chunk_iters": GS_CHUNK_ITERS,
            "shuffle_trajectory": shuffle_traj,
            "gap_trajectory": gap_traj,
        },
    }


def _streaming_bench():
    """A/B out-of-core streamed training against the in-memory fit on the
    same on-disk Avro dataset: identical FE logistic problem, streamed in
    fixed-shape blocks through the double-buffered prefetcher vs one
    materialized design matrix. Reports wall clock both ways, the prefetch
    hide ratio (decode seconds that never surfaced as a consumer stall),
    the peak-host-RSS delta of the streamed fit plus its deterministic
    staging bound, held-out AUC parity, and the post-warmup retrace count
    (must be 0). Emits ONE JSON line and writes BENCH_STREAMING.json; an
    exception emits an error line instead."""
    import resource
    import sys
    import tempfile
    import time as _time

    try:
        import jax

        if _SMOKE:
            jax.config.update("jax_platforms", "cpu")
        from photon_ml_tpu.estimators.game import (
            FixedEffectCoordinateConfiguration,
            GameEstimator,
        )
        from photon_ml_tpu.io.data_reader import (
            FeatureShardConfiguration,
            read_game_data,
            write_training_examples,
        )
        from photon_ml_tpu.opt import (
            GlmOptimizationConfiguration,
            RegularizationContext,
        )
        from photon_ml_tpu.streaming import (
            StreamingSource,
            reset_stream_trace_counts,
            stream_trace_counts,
        )
        from photon_ml_tpu.telemetry import get_registry
        from photon_ml_tpu.types import RegularizationType, TaskType

        summarize_telemetry = _bench_telemetry("streaming")
        rng = np.random.default_rng(SEED)
        w_true = rng.normal(size=D_ST).astype(np.float32)

        def _sample(n, seed):
            r = np.random.default_rng(seed)
            X = r.normal(size=(n, D_ST)).astype(np.float32)
            p = 1.0 / (1.0 + np.exp(-(X @ w_true)))
            y = (p > r.random(n)).astype(np.float32)
            return X, y

        def _records(X, y):
            for i in range(X.shape[0]):
                yield {
                    "label": float(y[i]),
                    "features": [
                        ("f", str(j), float(X[i, j])) for j in range(D_ST)
                    ],
                }

        X_tr, y_tr = _sample(N_ST_ROWS, SEED + 1)
        X_va, y_va = _sample(N_ST_VAL, SEED + 2)

        shard_configs = {
            "global": FeatureShardConfiguration(
                feature_bags=("features",), add_intercept=True
            ),
        }
        l2 = GlmOptimizationConfiguration(
            regularization=RegularizationContext(RegularizationType.L2),
            regularization_weight=0.1,
        )

        def _estimator():
            return GameEstimator(
                task=TaskType.LOGISTIC_REGRESSION,
                coordinates={
                    "fixed": FixedEffectCoordinateConfiguration("global", l2),
                },
            )

        with tempfile.TemporaryDirectory() as tmp:
            splits = np.linspace(0, N_ST_ROWS, N_ST_FILES + 1).astype(int)
            paths = []
            for i in range(N_ST_FILES):
                p = os.path.join(tmp, f"part-{i:05d}.avro")
                write_training_examples(
                    p, _records(X_tr[splits[i]:splits[i + 1]],
                                y_tr[splits[i]:splits[i + 1]])
                )
                paths.append(p)

            val_path = os.path.join(tmp, "val.avro")
            write_training_examples(val_path, _records(X_va, y_va))

            # --- streamed fit FIRST: ru_maxrss is a high-water mark, so the
            # in-memory fit (which materializes everything) must come after
            # for the streamed delta to mean anything
            rss0_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            t0 = _time.perf_counter()
            # default 2-file LRU decode cache: with more part files than
            # cache slots the streamed fit genuinely re-reads from disk, so
            # the peak-RSS delta measures out-of-core residency, not a
            # hidden whole-dataset cache. The decoded block cache spills to
            # disk in the same tmp dir; the COLD fit decodes Avro once and
            # writes entries, the WARM fit must reload every block via mmap
            # with zero decode work.
            source = StreamingSource.open(
                paths, shard_configs, block_rows=ST_BLOCK_ROWS,
                cache_dir=os.path.join(tmp, "_block_cache"),
            )
            open_s = _time.perf_counter() - t0
            reg = get_registry()

            def _stream_totals():
                return {
                    k: reg.counter_value(f"stream.{k}")
                    for k in (
                        "decode_s", "decode_work_s", "stall_s", "transfer_s",
                        "upload_hidden_s", "blocks", "cache_hit_blocks",
                        "cache_load_s", "h2d_bytes",
                        "residency.hbm_hit_blocks",
                        "residency.h2d_saved_bytes",
                    )
                }

            reset_stream_trace_counts()
            before = _stream_totals()
            t0 = _time.perf_counter()
            fit_st = _estimator().fit_streaming(
                source, prefetch_depth=ST_PREFETCH
            )
            stream_fit_s = _time.perf_counter() - t0
            totals = {
                k: v - before[k] for k, v in _stream_totals().items()
            }
            traces_cold = dict(stream_trace_counts())

            # warm repeat: every stream_* program must already be compiled
            # and every block must come from the cache (zero Avro work)
            before_warm = _stream_totals()
            t0 = _time.perf_counter()
            fit_warm = _estimator().fit_streaming(
                source, prefetch_depth=ST_PREFETCH
            )
            stream_warm_s = _time.perf_counter() - t0
            warm_totals = {
                k: v - before_warm[k] for k, v in _stream_totals().items()
            }
            traces_warm = dict(stream_trace_counts())
            retraces_after_warmup = sum(traces_warm.values()) - sum(
                traces_cold.values()
            )

            # --- convergence-plane fit: same warm solve with a
            # ConvergenceTracker attached, which routes every block through
            # the probe accumulation program (per-block partial loss / grad
            # norm / duality-gap estimate). Its wall vs the plain warm fit IS
            # the enabled-overhead measurement (the <2% budget); the final
            # epoch's per-block gaps land in the artifact — the signal a
            # DuHL-style gap-guided scheduler will consume.
            from photon_ml_tpu.telemetry import (
                ConvergenceTracker,
                convergence_report,
            )

            # warmup pass compiles the probe accumulation program so the
            # timed pass measures steady-state overhead, not a one-time trace
            warm_tracker = ConvergenceTracker(abort_on_divergence=False)
            _estimator().fit_streaming(
                source, prefetch_depth=ST_PREFETCH, progress=warm_tracker
            )
            warm_tracker.finish()
            tracker = ConvergenceTracker(abort_on_divergence=False)
            t0 = _time.perf_counter()
            fit_prog = _estimator().fit_streaming(
                source, prefetch_depth=ST_PREFETCH, progress=tracker
            )
            stream_prog_s = _time.perf_counter() - t0
            tracker.finish()
            prog_report = convergence_report(tracker.records)
            block_gaps = {
                str(i): round(float(v["gap_estimate"]), 6)
                for i, v in sorted(
                    (prog_report.get("blocks", {}).get("fixed", {})
                     .get("final_pass", {})).items()
                )
            }
            del fit_prog
            rss1_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss

            # --- in-memory comparator on the same files
            t0 = _time.perf_counter()
            mem_data, _, _ = read_game_data(
                paths, shard_configs, index_maps=source.index_maps
            )
            read_s = _time.perf_counter() - t0
            t0 = _time.perf_counter()
            fit_mem = _estimator().fit(mem_data)
            mem_fit_s = _time.perf_counter() - t0
            rss2_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss

            # validation read with the TRAINING index maps so scores align
            val_data, _, _ = read_game_data(
                [val_path], shard_configs, index_maps=source.index_maps
            )

            # --- hierarchical residency A/B (gap-pinned HBM set): warm
            # streamed fit again with the top-gap blocks held device-
            # resident across passes. The resident path routes through the
            # probe accumulation program in the SAME block order, so the
            # trajectory is bitwise-identical — AUC must match — while every
            # post-pin pass skips the residents' H2D upload entirely.
            res_blocks = 3 if _SMOKE else 10
            res_tracker = ConvergenceTracker(abort_on_divergence=False)
            traces_pre_res = dict(stream_trace_counts())
            before_res = _stream_totals()
            t0 = _time.perf_counter()
            fit_res = _estimator().fit_streaming(
                source, prefetch_depth=ST_PREFETCH,
                resident_blocks=res_blocks, progress=res_tracker,
            )
            res_fit_s = _time.perf_counter() - t0
            res_totals = {
                k: v - before_res[k] for k, v in _stream_totals().items()
            }
            res_tracker.finish()
            # residency is pure host-side bookkeeping: zero new programs
            residency_retraces = sum(
                stream_trace_counts().values()
            ) - sum(traces_pre_res.values())
            res_report = convergence_report(res_tracker.records)
            res_agg = res_report.get("residency", {}).get("fixed", {})
            # replay the pin/evict ledger to the final resident set, then
            # check it equals the top-k blocks by final-pass measured gap —
            # the "chosen by the probe, not static" gate
            resident_set: set = set()
            for rec in res_tracker.records:
                if rec.get("kind") != "residency":
                    continue
                if rec["action"] == "pin":
                    resident_set.add(int(rec["block"]))
                elif rec["action"] == "evict":
                    resident_set.discard(int(rec["block"]))
            res_gaps = {
                int(i): abs(float(v["gap_estimate"]))
                for i, v in (res_report.get("blocks", {}).get("fixed", {})
                             .get("final_pass", {})).items()
            }
            gap_topk = set(
                sorted(res_gaps, key=lambda i: -res_gaps[i])[:res_blocks]
            )
            resident_matches_gap_topk = bool(resident_set) and (
                resident_set == gap_topk
            )

            # --- DuHL gap-scheduling A/B (same shapes: zero new retraces
            # beyond the stochastic solver family, each traced once)
            gap_fields = _gap_schedule_ab(tmp)
        auc_stream = _auc(
            np.asarray(fit_st.model.score(val_data)), y_va
        )
        auc_mem = _auc(np.asarray(fit_mem.model.score(val_data)), y_va)
        auc_res = _auc(np.asarray(fit_res.model.score(val_data)), y_va)
        del fit_warm, fit_res

        def _hide(t):
            # wall-based: decode_s is decode-in-flight wall clock, so the
            # ratio is the share of that wall that never stalled the consumer
            return (
                max(0.0, (t["decode_s"] - t["stall_s"])) / t["decode_s"]
                if t["decode_s"] > 0 else 1.0
            )

        hide_ratio = _hide(totals)
        warm_hide_ratio = _hide(warm_totals)
        block_bytes = source.block_feature_bytes("global")
        payload = {
            "metric": "streaming_fit_wall_s",
            "value": round(stream_fit_s, 6),
            "unit": "seconds",
            "inmemory_fit_s": round(mem_fit_s, 6),
            "inmemory_read_s": round(read_s, 6),
            "stream_open_s": round(open_s, 6),
            "cold_epoch_s": round(stream_fit_s, 6),
            "warm_epoch_s": round(stream_warm_s, 6),
            "stream_vs_inmemory": round(stream_fit_s / mem_fit_s, 3),
            "warm_vs_inmemory": round(stream_warm_s / mem_fit_s, 3),
            "rows": N_ST_ROWS,
            "dim": D_ST + 1,
            "num_files": N_ST_FILES,
            "num_blocks": source.plan.num_blocks,
            "block_rows": ST_BLOCK_ROWS,
            "prefetch_depth": ST_PREFETCH,
            "blocks_streamed": int(totals["blocks"]),
            "decode_s": round(totals["decode_s"], 6),
            "decode_work_s": round(totals["decode_work_s"], 6),
            "stall_s": round(totals["stall_s"], 6),
            "transfer_s": round(totals["transfer_s"], 6),
            "upload_hidden_s": round(totals["upload_hidden_s"], 6),
            "cache_hit_blocks": int(totals["cache_hit_blocks"]),
            "cache_load_s": round(totals["cache_load_s"], 6),
            "cold_h2d_bytes": int(totals["h2d_bytes"]),
            "warm_h2d_bytes": int(warm_totals["h2d_bytes"]),
            "warm_decode_work_s": round(warm_totals["decode_work_s"], 6),
            "warm_cache_hit_blocks": int(warm_totals["cache_hit_blocks"]),
            "warm_blocks_streamed": int(warm_totals["blocks"]),
            "prefetch_hide_ratio": round(hide_ratio, 4),
            "warm_prefetch_hide_ratio": round(warm_hide_ratio, 4),
            # achieved decode-pool parallelism: summed per-file decode work
            # over decode-in-flight wall clock (1.0 = serial; > 1 means the
            # file-parallel pool genuinely overlapped decodes)
            "decode_parallelism": round(
                totals["decode_work_s"] / totals["decode_s"]
                if totals["decode_s"] > 0 else 0.0, 4
            ),
            "warm_decode_parallelism": round(
                warm_totals["decode_work_s"] / warm_totals["decode_s"]
                if warm_totals["decode_s"] > 0 else 0.0, 4
            ),
            # convergence plane: warm fit with the tracker + block probes on
            "progress_fit_s": round(stream_prog_s, 6),
            "progress_overhead_vs_warm": round(
                stream_prog_s / stream_warm_s - 1.0, 4
            ),
            "progress_updates": int(prog_report.get("num_updates", 0)),
            "block_gap_estimates": block_gaps,
            "peak_rss_stream_delta_mb": round((rss1_kb - rss0_kb) / 1024, 1),
            "peak_rss_inmemory_delta_mb": round((rss2_kb - rss1_kb) / 1024, 1),
            "staging_bound_mb": round(
                ST_PREFETCH * block_bytes / (1024 * 1024), 1
            ),
            "auc_stream": round(auc_stream, 6),
            "auc_inmemory": round(auc_mem, 6),
            "auc_delta": round(abs(auc_stream - auc_mem), 6),
            "retraces_after_warmup": int(retraces_after_warmup),
            # hierarchical residency arm: warm fit with the gap-pinned HBM
            # set — same trajectory, a resident-fraction fewer H2D bytes
            "residency": {
                "resident_blocks": res_blocks,
                "warm_epoch_s": round(res_fit_s, 6),
                "h2d_bytes": int(res_totals["h2d_bytes"]),
                "h2d_ratio": round(
                    res_totals["h2d_bytes"] / warm_totals["h2d_bytes"], 4
                ) if warm_totals["h2d_bytes"] else 0.0,
                "hbm_hit_blocks": int(
                    res_totals["residency.hbm_hit_blocks"]
                ),
                "h2d_saved_bytes": int(
                    res_totals["residency.h2d_saved_bytes"]
                ),
                "resident_set": sorted(resident_set),
                "pins": int(res_agg.get("pins", 0)),
                "evictions": int(res_agg.get("evictions", 0)),
                "resident_matches_gap_topk": bool(resident_matches_gap_topk),
                "retraces": int(residency_retraces),
                "auc": round(auc_res, 6),
                "auc_delta": round(abs(auc_res - auc_stream), 6),
            },
            # overlap physics: with decode_workers=0 (single-CPU hosts) the
            # decode thread and the solver timeshare one core, so the hide
            # ratio is bounded by compute/decode; readers gate on cpus
            "cpus": os.cpu_count() or 1,
            "decode_workers": source.decode_workers,
            "backend": jax.default_backend(),
            **gap_fields,
            "telemetry": summarize_telemetry(),
        }
        print(json.dumps(payload))
        if not _SMOKE or _env_flag("BENCH_STREAMING_WRITE"):
            with open(_STREAMING_PATH, "w") as f:
                json.dump(payload, f, indent=2)
        _append_history(payload, "streaming")
        _append_history(
            {
                "metric": "gap_vs_shuffle_visits",
                "value": payload["gap_vs_shuffle_visits"],
                "unit": "x_fewer_block_visits_to_target",
            },
            "gap_schedule",
        )
        _append_history(
            {
                "metric": "residency_warm_h2d_ratio",
                "value": payload["residency"]["h2d_ratio"],
                "unit": "x_of_warm_h2d_bytes",
            },
            "residency",
        )
    except Exception as e:  # noqa: BLE001 - one JSON line per exit path
        print(json.dumps({
            "metric": "streaming_fit_wall_s",
            "error": f"{type(e).__name__}: {e}",
        }))
        sys.exit(1)


# --- multi-host cluster bench -----------------------------------------------
# Emulated multi-host mesh on one box: worker subprocesses stream their
# assigned block shares with an EMULATED per-block device latency (sleeps in
# separate processes genuinely overlap, so throughput scales with hosts the
# way real device time would — the PR 7 precedent; device_latency_emulated
# marks the artifact). The real decode work is pushed to the per-host block
# cache so the measured pass time is latency-dominated, not CPU-timeshared.
MH_HOSTS = (1, 2) if _SMOKE else (1, 2, 4)  # emulated host counts
MH_NUM_BLOCKS = 16                          # streamed blocks (2 part files)
MH_BLOCK_ROWS = 96 if _SMOKE else 768       # rows per block
MH_DIM = 24                                 # feature dim (+1 intercept)
MH_VAL = 512 if _SMOKE else 4096            # held-out rows
MH_LATENCY_S = 0.02 if _SMOKE else 0.06     # emulated per-block latency
MH_KILL_AFTER = 5                           # chaos: host 1 dies mid-pass
_MULTIHOST_PATH = os.path.join(_REPO, "BENCH_MULTIHOST.json")


def _multihost_bench():
    """Benchmark the cluster plane (parallel/cluster): streamed full-batch
    data-parallel CD across 1/2/4 emulated worker hosts on the same Avro
    workload. Reports throughput scaling vs the 1-host cluster arm (the
    same protocol path, so the ratio isolates data-parallel speedup from
    coordinator overhead), held-out AUC parity vs the pure in-process
    single-host fit, and a killed-host-mid-epoch chaos arm that must
    finish with the dead host's blocks reassigned (recovery visible in the
    progress ledger + counters). Emits ONE JSON line and writes
    BENCH_MULTIHOST.json."""
    import sys
    import tempfile
    import time as _time

    try:
        import jax

        # the emulated mesh is a CPU drill by construction
        jax.config.update("jax_platforms", "cpu")
        from photon_ml_tpu.estimators.game import (
            FixedEffectCoordinateConfiguration,
            GameEstimator,
        )
        from photon_ml_tpu.io.data_reader import (
            FeatureShardConfiguration,
            read_game_data,
            write_training_examples,
        )
        from photon_ml_tpu.opt import (
            GlmOptimizationConfiguration,
            RegularizationContext,
        )
        from photon_ml_tpu.parallel.cluster import ClusterPlane
        from photon_ml_tpu.streaming import StreamingSource
        from photon_ml_tpu.telemetry import (
            ConvergenceTracker,
            get_registry,
        )
        from photon_ml_tpu.types import RegularizationType, TaskType

        summarize_telemetry = _bench_telemetry("multihost")
        n_rows = MH_NUM_BLOCKS * MH_BLOCK_ROWS
        rng = np.random.default_rng(SEED + 11)
        w_true = rng.normal(size=MH_DIM).astype(np.float32) * 0.7

        def _sample(n, seed):
            r = np.random.default_rng(seed)
            X = r.normal(size=(n, MH_DIM)).astype(np.float32)
            p = 1.0 / (1.0 + np.exp(-(X @ w_true)))
            y = (p > r.random(n)).astype(np.float32)
            return X, y

        def _records(X, y):
            for i in range(X.shape[0]):
                yield {
                    "label": float(y[i]),
                    "features": [
                        ("f", str(j), float(X[i, j])) for j in range(MH_DIM)
                    ],
                }

        X_tr, y_tr = _sample(n_rows, SEED + 12)
        X_va, y_va = _sample(MH_VAL, SEED + 13)
        shard_configs = {
            "global": FeatureShardConfiguration(
                feature_bags=("features",), add_intercept=True
            ),
        }
        with tempfile.TemporaryDirectory() as tmp:
            # 2 part files: both stay in the workers' default decode LRU,
            # so interleaved block assignments never thrash file decodes
            train_dir = os.path.join(tmp, "train")
            os.makedirs(train_dir)
            half = n_rows // 2
            for i, (lo, hi) in enumerate(((0, half), (half, n_rows))):
                write_training_examples(
                    os.path.join(train_dir, f"part-{i:05d}.avro"),
                    _records(X_tr[lo:hi], y_tr[lo:hi]),
                )
            val_path = os.path.join(tmp, "val.avro")
            write_training_examples(val_path, _records(X_va, y_va))
            # the worker CLI rebuilds this config; LBFGS caps keep the
            # pass count identical-ish across arms and the wall bounded
            config_path = os.path.join(tmp, "game.json")
            with open(config_path, "w") as f:
                json.dump({
                    "feature_shards": {
                        "global": {"feature_bags": ["features"],
                                   "add_intercept": True},
                    },
                    "coordinates": {
                        "fixed": {
                            "type": "fixed", "feature_shard": "global",
                            "optimizer": {
                                "optimizer": "LBFGS", "max_iterations": 8,
                                "tolerance": 0.0, "regularization": "L2",
                                "regularization_weight": 0.1,
                            },
                        },
                    },
                }, f)

            def _open_source():
                return StreamingSource.open(
                    [train_dir], shard_configs, block_rows=MH_BLOCK_ROWS,
                    cache_dir=None,
                )

            def _val_auc(fit):
                val_data, _, _ = read_game_data(
                    [val_path], shard_configs,
                    index_maps=_open_source().index_maps,
                )
                return _auc(np.asarray(fit.model.score(val_data)), y_va)

            from photon_ml_tpu.opt import OptimizerConfig

            # tolerance=0 pins every arm to exactly 8 LBFGS iterations:
            # the partitioned (f, g) sums differ from single-host only by
            # fp reassociation, but near a 1e-6 stopping threshold that
            # noise can flip the convergence check and give arms
            # different pass counts, making walls incomparable
            cfg8 = GlmOptimizationConfiguration(
                optimizer_config=OptimizerConfig(
                    max_iterations=8, tolerance=0.0
                ),
                regularization=RegularizationContext(RegularizationType.L2),
                regularization_weight=0.1,
            )

            def _estimator8():
                return GameEstimator(
                    task=TaskType.LOGISTIC_REGRESSION,
                    coordinates={
                        "fixed": FixedEffectCoordinateConfiguration(
                            "global", cfg8
                        ),
                    },
                )

            # --- pure in-process single-host reference (no cluster, no
            # emulated latency): the AUC parity anchor
            src = _open_source()
            fit_solo = _estimator8().fit_streaming(src, prefetch_depth=2)
            auc_solo = _val_auc(fit_solo)

            def _cluster_arm(hosts, kill_host=None, tracker=None):
                plane = ClusterPlane.launch(
                    num_hosts=hosts,
                    num_blocks=MH_NUM_BLOCKS,
                    train_dirs=[train_dir],
                    coordinate_config=config_path,
                    task="LOGISTIC_REGRESSION",
                    feature_shard="global",
                    block_rows=MH_BLOCK_ROWS,
                    block_cache_dir=os.path.join(tmp, "wcache"),
                    block_latency_s=MH_LATENCY_S,
                    kill_host=kill_host,
                    heartbeat_timeout_s=60.0,
                    log_dir=os.path.join(tmp, f"logs-{hosts}h"),
                )
                # skew attribution piggybacks on the partial replies —
                # same message count, so it cannot perturb the scaling
                plane.coordinator.enable_telemetry()
                # count passes so throughput normalizes to blocks/s: fp
                # reassociation across partitions can still flip a rare
                # borderline line-search trial, and wall alone would then
                # compare different amounts of work
                passes = [0]
                inner_pass = plane.coordinator.distributed_pass

                def counted_pass(w):
                    passes[0] += 1
                    return inner_pass(w)

                plane.coordinator.distributed_pass = counted_pass
                try:
                    # warm the workers' jit + block caches with one
                    # throwaway pass so the timed fit measures streaming,
                    # not first-compile
                    if kill_host is None:
                        plane.distributed_pass(
                            np.zeros(MH_DIM + 1, dtype=np.float32)
                        )
                        plane.drain_events()
                        plane.drain_pass_profiles()
                        passes[0] = 0
                    t0 = _time.perf_counter()
                    fit = _estimator8().fit_streaming(
                        _open_source(), prefetch_depth=2, cluster=plane,
                        progress=tracker,
                    )
                    wall = _time.perf_counter() - t0
                    events = plane.drain_events()
                finally:
                    plane.close()
                return fit, wall, passes[0], events

            def _skew_summary(cluster_passes):
                """Per-arm skew/comm-wait attribution from the
                coordinator's pass profiles (the analyze_run --cluster
                decomposition, aggregated)."""
                if not cluster_passes:
                    return None
                wall = sum(p["wall_s"] for p in cluster_passes)
                busy = sum(p["busy_s"] for p in cluster_passes)
                wait = sum(p["allreduce_wait_s"] for p in cluster_passes)
                bubble = sum(p["bubble_s"] for p in cluster_passes)
                idx = [p["straggler_index"] for p in cluster_passes]
                hosts_busy: dict = {}
                for p in cluster_passes:
                    for h, row in (p.get("hosts") or {}).items():
                        hosts_busy[str(h)] = round(
                            hosts_busy.get(str(h), 0.0)
                            + float(row.get("busy_s", 0.0)), 4
                        )
                return {
                    "passes": len(cluster_passes),
                    "allreduce_wait_mean_s": round(
                        wait / len(cluster_passes), 4
                    ),
                    "allreduce_wait_frac": round(wait / wall, 4),
                    "coordinator_bubble_frac": round(bubble / wall, 4),
                    "busy_frac": round(busy / wall, 4),
                    "straggler_index_mean": round(
                        sum(idx) / len(idx), 4
                    ),
                    "attribution_coverage": round(
                        (busy + wait + bubble) / wall, 4
                    ),
                    "hosts_busy_s": hosts_busy,
                }

            arms = {}
            for hosts in MH_HOSTS:
                # the tracker rides the bench ledger, so cluster_pass /
                # host_pass records land in multihost-ledger.jsonl (CI's
                # cluster observability gate replays them)
                mh_tracker = ConvergenceTracker(
                    ledger=summarize_telemetry.run.ledger,
                    abort_on_divergence=False,
                )
                fit, wall, passes, _ = _cluster_arm(
                    hosts, tracker=mh_tracker
                )
                mh_tracker.finish()
                arms[hosts] = {
                    "fit_wall_s": round(wall, 3),
                    "passes": passes,
                    "blocks_per_s": round(
                        passes * MH_NUM_BLOCKS / wall, 2
                    ),
                    "auc": round(_val_auc(fit), 6),
                    "skew": _skew_summary(mh_tracker.cluster_passes),
                }

            base_rate = arms[MH_HOSTS[0]]["blocks_per_s"]
            for hosts, arm in arms.items():
                arm["throughput_vs_1host"] = round(
                    arm["blocks_per_s"] / base_rate, 3
                )
            auc_delta = max(
                abs(arm["auc"] - auc_solo) for arm in arms.values()
            )

            # --- chaos arm: 2 hosts, host 1 killed mid-first-pass; the fit
            # must complete with its blocks reassigned, and the recovery
            # must be visible in the progress ledger
            reg = get_registry()
            hf0 = reg.counter_value("cluster.host_failures")
            br0 = reg.counter_value("cluster.blocks_reassigned")
            tracker = ConvergenceTracker(abort_on_divergence=False)
            tracker.attach_failure_sink()
            fit_chaos, chaos_wall, _, _ = _cluster_arm(
                2, kill_host=(1, MH_KILL_AFTER), tracker=tracker,
            )
            tracker.finish()
            chaos_auc = _val_auc(fit_chaos)
            cluster_recs = [
                r for r in tracker.records if r.get("kind") == "cluster"
            ]
            ledger_events = sorted({r["event"] for r in cluster_recs})
            host_failures = reg.counter_value("cluster.host_failures") - hf0
            blocks_reassigned = (
                reg.counter_value("cluster.blocks_reassigned") - br0
            )

        payload = {
            "metric": "multihost_speedup_2hosts",
            "value": arms.get(2, {}).get("throughput_vs_1host", 0.0),
            "unit": "x_blocks_per_s_vs_1host_cluster",
            "hosts": {str(h): arms[h] for h in arms},
            "speedup_4hosts": arms.get(4, {}).get(
                "throughput_vs_1host", None
            ),
            "auc_singlehost": round(auc_solo, 6),
            "auc_parity_delta": round(auc_delta, 6),
            # headline skew/comm-wait attribution for the 2-host arm (the
            # per-arm breakdown lives under hosts.<n>.skew)
            "allreduce_wait_frac_2hosts": (
                arms.get(2, {}).get("skew") or {}
            ).get("allreduce_wait_frac"),
            "straggler_index_2hosts": (
                arms.get(2, {}).get("skew") or {}
            ).get("straggler_index_mean"),
            "skew_attribution_coverage_2hosts": (
                arms.get(2, {}).get("skew") or {}
            ).get("attribution_coverage"),
            "chaos": {
                "hosts": 2,
                "killed_host": 1,
                "killed_after_blocks": MH_KILL_AFTER,
                "completed": True,
                "fit_wall_s": round(chaos_wall, 3),
                "auc": round(chaos_auc, 6),
                "auc_delta_vs_singlehost": round(
                    abs(chaos_auc - auc_solo), 6
                ),
                "host_failures": int(host_failures),
                "blocks_reassigned": int(blocks_reassigned),
                "ledger_events": ledger_events,
                "ledger_cluster_records": len(cluster_recs),
                "skew": _skew_summary(tracker.cluster_passes),
            },
            "rows": n_rows,
            "dim": MH_DIM + 1,
            "num_blocks": MH_NUM_BLOCKS,
            "block_rows": MH_BLOCK_ROWS,
            "block_latency_s": MH_LATENCY_S,
            "device_latency_emulated": True,
            "cpus": os.cpu_count() or 1,
            "backend": "cpu",
            "telemetry": summarize_telemetry(),
        }
        print(json.dumps(payload))
        if not _SMOKE or _env_flag("BENCH_MULTIHOST_WRITE"):
            with open(_MULTIHOST_PATH, "w") as f:
                json.dump(payload, f, indent=2)
        _append_history(payload, "multihost")
        _append_history(
            {
                "metric": "multihost_auc_parity_delta",
                "value": payload["auc_parity_delta"],
                "unit": "abs_auc_delta_vs_singlehost",
            },
            "multihost-parity",
        )
    except Exception as e:  # noqa: BLE001 - one JSON line per exit path
        print(json.dumps({
            "metric": "multihost_speedup_2hosts",
            "error": f"{type(e).__name__}: {e}",
        }))
        sys.exit(1)


# --- adaptive random-effect solve bench ------------------------------------
N_AD_ENT = 64 if _SMOKE else 1024           # entities in the skewed bucket
N_AD_HARD = 6 if _SMOKE else 64             # slow-converging tail entities
S_AD_MIN, S_AD_MAX = 5, 500                 # samples/entity (ISSUE workload)
D_AD = 6                                    # per-entity feature dim
_RE_ADAPTIVE_PATH = os.path.join(_REPO, "BENCH_RE_ADAPTIVE.json")


def _re_adaptive_bench():
    """Benchmark the convergence-adaptive random-effect driver against the
    one-shot lockstep vmap on a skewed-convergence warm-started workload:
    most entities are warm-started at their optimum (converge in a couple of
    iterations), a small tail sees fresh near-separable data and runs long —
    the nearline re-solve profile. Reports wall-clock speedup and
    lane-iteration efficiency from SolverStats, and writes
    BENCH_RE_ADAPTIVE.json. Emits ONE JSON line; an exception emits an
    error line instead."""
    import sys
    import time as _time

    try:
        import jax

        if _SMOKE:
            jax.config.update("jax_platforms", "cpu")
        from photon_ml_tpu.data import (
            RandomEffectDataConfiguration,
            build_random_effect_dataset,
        )
        from photon_ml_tpu.estimators.random_effect import train_random_effects
        from photon_ml_tpu.opt import (
            AdaptiveSolveConfig,
            GlmOptimizationConfiguration,
            RegularizationContext,
        )
        from photon_ml_tpu.types import RegularizationType, TaskType

        summarize_telemetry = _bench_telemetry("re-adaptive")
        rng = np.random.default_rng(SEED)
        rows, cols, vals, ids = [], [], [], []
        labels_base, labels_fresh = [], []
        r = 0
        for e in range(N_AD_ENT):
            eid = f"m{e:05d}"
            hard = e < N_AD_HARD
            n_e = S_AD_MAX if hard else int(rng.integers(S_AD_MIN, 30))
            w_e = rng.normal(size=D_AD).astype(np.float32) * 0.5
            w_fresh = rng.normal(size=D_AD).astype(np.float32) * 10.0
            for _ in range(n_e):
                x = rng.normal(size=D_AD).astype(np.float32)
                z = float(x @ w_e)
                yb = 1.0 if rng.random() < 1.0 / (1.0 + np.exp(-z)) else 0.0
                # the tail's fresh batch is near-separable: many iterations
                yf = yb if not hard else (1.0 if float(x @ w_fresh) > 0 else 0.0)
                for c in range(D_AD):
                    rows.append(r)
                    cols.append(c)
                    vals.append(float(x[c]))
                ids.append(eid)
                labels_base.append(yb)
                labels_fresh.append(yf)
                r += 1

        dcfg = RandomEffectDataConfiguration(random_effect_type="m", num_buckets=1)

        def _ds(lab):
            return build_random_effect_dataset(
                ids, np.array(rows), np.array(cols),
                np.array(vals, np.float32), D_AD,
                np.array(lab, np.float32), dcfg,
            )

        ds_base, ds_fresh = _ds(labels_base), _ds(labels_fresh)
        base = dict(
            regularization=RegularizationContext(RegularizationType.L2),
            regularization_weight=1e-6,
        )
        cfg_ad = GlmOptimizationConfiguration(
            **base, adaptive=AdaptiveSolveConfig(enabled=True)
        )
        cfg_os = GlmOptimizationConfiguration(
            **base, adaptive=AdaptiveSolveConfig(enabled=False)
        )
        task = TaskType.LOGISTIC_REGRESSION

        warm, _ = train_random_effects(ds_base, task, cfg_os)

        def _run(cfg, stats=None):
            t0 = _time.perf_counter()
            train_random_effects(
                ds_fresh, task, cfg, initial_model=warm, stats_out=stats
            )
            return _time.perf_counter() - t0

        _run(cfg_ad)  # compile both paths before timing
        _run(cfg_os)
        reps = 2 if _SMOKE else 5
        stats: list = []
        adaptive_s = min(_run(cfg_ad, stats if i == 0 else None) for i in range(reps))
        oneshot_s = min(_run(cfg_os) for _ in range(reps))

        executed = sum(s.executed_lane_iterations for s in stats)
        lockstep = sum(s.lockstep_lane_iterations for s in stats)
        payload = {
            "metric": "re_adaptive_speedup",
            "value": round(oneshot_s / adaptive_s, 4) if adaptive_s > 0 else None,
            "unit": "x_vs_oneshot",
            "adaptive_wall_s": round(adaptive_s, 6),
            "oneshot_wall_s": round(oneshot_s, 6),
            "executed_lane_iterations": int(executed),
            "lockstep_lane_iterations": int(lockstep),
            "lane_iteration_savings": (
                round(lockstep / executed, 4) if executed else None
            ),
            "wasted_lane_fraction": (
                round(max(s.wasted_lane_fraction for s in stats), 4)
                if stats else None
            ),
            "rounds": [s.rounds for s in stats],
            "dispatch_widths": [list(s.dispatch_widths) for s in stats],
            "chunk_iters": cfg_ad.adaptive.chunk_iters,
            "n_entities": N_AD_ENT,
            "n_hard": N_AD_HARD,
            "backend": jax.default_backend(),
            "telemetry": summarize_telemetry(),
        }
        print(json.dumps(payload))
        if not _SMOKE or _env_flag("BENCH_RE_ADAPTIVE_WRITE"):
            with open(_RE_ADAPTIVE_PATH, "w") as f:
                json.dump(payload, f, indent=2)
        _append_history(payload, "re-adaptive")
    except Exception as e:  # noqa: BLE001 - one JSON line per exit path
        print(json.dumps({
            "metric": "re_adaptive_speedup",
            "error": f"{type(e).__name__}: {e}",
        }))
        sys.exit(1)


N_CD_USERS = 64 if _SMOKE else 1500         # per-user RE entities
N_CD_ITEMS = 32 if _SMOKE else 400          # per-item RE entities
N_CD_ROWS_PER_USER = 12 if _SMOKE else 80   # rows per user
D_CD_FE = 32 if _SMOKE else 256             # global feature dim
D_CD_RE = 8                                 # per-entity feature dim
_CD_SCORES_PATH = os.path.join(_REPO, "BENCH_CD_SCORES.json")


def _cd_scores_bench():
    """Benchmark the device-resident CD score plane against the host numpy
    plane on a 1-FE + 2-RE GLMix fit. Solver time (train_glm /
    train_random_effects, block_until_ready'd) is measured separately and
    subtracted, so the reported reduction isolates the CD driver's own
    overhead: score-plane algebra, residual regrouping, and host<->device
    row transfers. Writes BENCH_CD_SCORES.json. Emits ONE JSON line; an
    exception emits an error line instead."""
    import sys
    import time as _time

    try:
        import jax

        if _SMOKE:
            jax.config.update("jax_platforms", "cpu")
        from photon_ml_tpu.algorithm import coordinate as coord_mod
        from photon_ml_tpu.data.game_data import FeatureShard, GameData
        from photon_ml_tpu.data.random_effect import (
            RandomEffectDataConfiguration,
        )
        from photon_ml_tpu.estimators.game import (
            FixedEffectCoordinateConfiguration,
            GameEstimator,
            RandomEffectCoordinateConfiguration,
        )
        from photon_ml_tpu.opt import (
            GlmOptimizationConfiguration,
            RegularizationContext,
        )
        from photon_ml_tpu.opt.config import OptimizerConfig
        from photon_ml_tpu.types import RegularizationType, TaskType

        summarize_telemetry = _bench_telemetry("cd-scores")
        rng = np.random.default_rng(SEED)
        n = N_CD_USERS * N_CD_ROWS_PER_USER
        Xg = rng.normal(size=(n, D_CD_FE)).astype(np.float32) * 0.3
        if not _SMOKE:
            # realistic sparse global shard (~5% density) — keeps the FE
            # solve and dataset build proportionate at 100k+ rows
            Xg *= rng.random(size=Xg.shape) < 0.05
        Xu = rng.normal(size=(n, D_CD_RE)).astype(np.float32)
        Xi = rng.normal(size=(n, D_CD_RE)).astype(np.float32)
        user_ids = np.repeat(
            [f"u{i:05d}" for i in range(N_CD_USERS)], N_CD_ROWS_PER_USER
        )
        # skewed item popularity — realistic RE bucket spread
        item_ids = np.array([
            f"i{int(v):05d}"
            for v in np.minimum(
                rng.zipf(1.7, size=n) - 1, N_CD_ITEMS - 1
            )
        ])
        w_fixed = rng.normal(size=D_CD_FE).astype(np.float32) * 0.1
        z = Xg @ w_fixed + 0.3 * rng.normal(size=n).astype(np.float32)
        y = z.astype(np.float32)

        def _coo(X):
            rows, cols = np.nonzero(X)
            return FeatureShard(
                rows=rows, cols=cols, vals=X[rows, cols], dim=X.shape[1]
            )

        data = GameData(
            labels=y,
            feature_shards={
                "global": _coo(Xg), "per_user": _coo(Xu), "per_item": _coo(Xi),
            },
            id_tags={"userId": user_ids, "itemId": item_ids},
        )
        # cheap solves: the bench isolates DRIVER overhead, so solver time
        # (subtracted below) is kept small relative to the plane work
        opt = GlmOptimizationConfiguration(
            regularization=RegularizationContext(RegularizationType.L2),
            regularization_weight=1.0,
            optimizer_config=OptimizerConfig.lbfgs(max_iterations=4),
        )
        coords = {
            "fixed": FixedEffectCoordinateConfiguration("global", opt),
            "per-user": RandomEffectCoordinateConfiguration(
                feature_shard="per_user",
                data=RandomEffectDataConfiguration(random_effect_type="userId"),
                optimizer=opt,
            ),
            "per-item": RandomEffectCoordinateConfiguration(
                feature_shard="per_item",
                data=RandomEffectDataConfiguration(random_effect_type="itemId"),
                optimizer=opt,
            ),
        }

        # monkeypatched timing wrappers isolate solver wall-clock
        solver_s = [0.0]
        real_glm, real_re = coord_mod.train_glm, coord_mod.train_random_effects

        def _timed(fn):
            # block on the ARRAYS inside the result: train_glm returns
            # [GlmFit] (a plain dataclass, opaque to block_until_ready — a
            # bare block on it returns immediately and the solve's async
            # compute would leak into the driver-overhead measurement),
            # train_random_effects returns (RandomEffectModel, diag)
            def wrapper(*a, **kw):
                t0 = _time.perf_counter()
                out = fn(*a, **kw)
                head = out[0]
                if hasattr(head, "model"):        # GlmFit
                    jax.block_until_ready((head.model, head.result))
                elif hasattr(head, "coefficients"):  # RandomEffectModel
                    jax.block_until_ready(head.coefficients)
                else:
                    jax.block_until_ready(head)
                solver_s[0] += _time.perf_counter() - t0
                return out
            return wrapper

        # datasets are built ONCE and shared (the one-time entity grouping is
        # not CD driver overhead); only _run_fit is timed
        builder = GameEstimator(
            task=TaskType.LINEAR_REGRESSION,
            coordinates=coords,
            num_outer_iterations=3,
        )
        built = {
            cid: builder._build_coordinate(cid, cfg, data)
            for cid, cfg in builder.coordinate_configs.items()
        }

        coord_mod.train_glm = _timed(real_glm)
        coord_mod.train_random_effects = _timed(real_re)
        try:
            def _fit(plane):
                est = GameEstimator(
                    task=TaskType.LINEAR_REGRESSION,
                    coordinates=coords,
                    num_outer_iterations=3,
                    score_plane=plane,
                )
                solver_s[0] = 0.0
                t0 = _time.perf_counter()
                fit = est._run_fit(built, data, None, None, None)
                wall = _time.perf_counter() - t0
                return est, fit, wall, solver_s[0]

            _fit("host")      # warmup: compiles + caches for both planes
            _fit("device")
            reps = 2 if _SMOKE else 3
            runs = {}
            for plane in ("host", "device"):
                best = None
                for _ in range(reps):
                    est, fit, wall, solve = _fit(plane)
                    overhead = wall - solve
                    if best is None or overhead < best[3]:
                        best = (est, fit, wall, overhead)
                runs[plane] = best
        finally:
            coord_mod.train_glm = real_glm
            coord_mod.train_random_effects = real_re

        est_h, fit_h, wall_h, over_h = runs["host"]
        est_d, fit_d, wall_d, over_d = runs["device"]
        parity = float(np.max(np.abs(
            np.asarray(fit_h.model.score(data))
            - np.asarray(fit_d.model.score(data))
        )))
        reduction = 1.0 - over_d / over_h if over_h > 0 else None
        payload = {
            "metric": "cd_score_plane_overhead_reduction",
            "value": round(reduction, 4) if reduction is not None else None,
            "unit": "fraction_vs_host_plane",
            "host_wall_s": round(wall_h, 6),
            "device_wall_s": round(wall_d, 6),
            "host_overhead_s": round(over_h, 6),
            "device_overhead_s": round(over_d, 6),
            "parity_max_abs_diff": parity,
            "host_transfers": est_h.last_transfer_stats.snapshot(),
            "device_transfers": est_d.last_transfer_stats.snapshot(),
            "num_rows": n,
            "num_coordinates": len(coords),
            "outer_iterations": 3,
            "backend": jax.default_backend(),
        }
        from photon_ml_tpu.telemetry import get_registry

        # the telemetry transfer totals reflect the device-plane winner
        get_registry().record_transfer_stats(est_d.last_transfer_stats)
        payload["telemetry"] = summarize_telemetry()
        print(json.dumps(payload))
        if not _SMOKE or _env_flag("BENCH_CD_SCORES_WRITE"):
            with open(_CD_SCORES_PATH, "w") as f:
                json.dump(payload, f, indent=2)
        _append_history(payload, "cd-scores")
    except Exception as e:  # noqa: BLE001 - one JSON line per exit path
        print(json.dumps({
            "metric": "cd_score_plane_overhead_reduction",
            "error": f"{type(e).__name__}: {e}",
        }))
        sys.exit(1)


N_CA_USERS = 24 if _SMOKE else 160          # per-user RE entities
N_CA_ITEMS = 12 if _SMOKE else 48           # per-item RE entities (zipf-skewed)
N_CA_ROWS_PER_USER = 10 if _SMOKE else 40   # training rows per user
N_CA_HOLD_PER_USER = 4 if _SMOKE else 10    # held-out rows per user (AUC)
D_CA_FE = 16 if _SMOKE else 96              # global feature dim
D_CA_RE = 4 if _SMOKE else 8                # per-entity feature dim
N_CA_OUTER = 2 if _SMOKE else 6             # outer CD iterations
CA_STALENESS = 1                            # async staleness bound
# Emulated device latency (CPU-only hosts): every solver call sleeps this
# fixed amount after its compute completes, modelling a blocking
# accelerator call whose device time dominates host glue. A CONSTANT (not
# a multiple of measured compute) keeps the two arms' latency models
# identical by construction — measuring compute under the async arm's
# core contention would inflate its own sleeps. See _cd_async_bench.
CA_EMU_LATENCY_S = 0.15 if _SMOKE else 1.0
_CD_ASYNC_PATH = os.path.join(_REPO, "BENCH_CD_ASYNC.json")


def _cd_async_bench():
    """Benchmark the bounded-staleness async CD schedule against the sync
    loop on a skewed logistic GLMix fit (1 FE + 2 RE, zipf item popularity
    — the --re-adaptive-style profile). Reports the outer-iteration
    wall-clock speedup, held-out AUC of both arms, the per-phase overlap
    attributed by the ledger analyzer, and the pow2 retrace parity. Writes
    BENCH_CD_ASYNC.json. Emits ONE JSON line; an exception emits an error
    line instead.

    Accelerator emulation: the schedule's win is overlapping device solve
    latency with other coordinates' work, which is unmeasurable on a
    CPU-only host (host and "device" share the cores, so there is nothing
    to hide latency behind). When the default backend is cpu, every solver
    entry point therefore sleeps a fixed CA_EMU_LATENCY_S after the solve
    completes — a GIL-releasing stand-in for the blocking device call both
    schedules would make on a real accelerator, applied IDENTICALLY to
    both arms so the ratio compares schedules, not workloads. The artifact
    is labelled ``device_latency_emulated`` so downstream readers can tell
    the two regimes apart; on an accelerator backend the emulation is off
    and the numbers are direct."""
    import sys
    import time as _time

    try:
        import jax

        if _SMOKE:
            jax.config.update("jax_platforms", "cpu")
        from photon_ml_tpu.algorithm import coordinate as coord_mod
        from photon_ml_tpu.data.game_data import FeatureShard, GameData
        from photon_ml_tpu.data.random_effect import (
            RandomEffectDataConfiguration,
        )
        from photon_ml_tpu.estimators.game import (
            FixedEffectCoordinateConfiguration,
            GameEstimator,
            RandomEffectCoordinateConfiguration,
        )
        from photon_ml_tpu.estimators.random_effect import solver_trace_counts
        from photon_ml_tpu.opt import (
            GlmOptimizationConfiguration,
            RegularizationContext,
        )
        from photon_ml_tpu.opt.config import OptimizerConfig
        from photon_ml_tpu.telemetry.analyze import analyze_ledger
        from photon_ml_tpu.types import RegularizationType, TaskType

        summarize_telemetry = _bench_telemetry("cd-async")
        rng = np.random.default_rng(SEED)

        def _rows(n_per_user):
            n = N_CA_USERS * n_per_user
            Xg = rng.normal(size=(n, D_CA_FE)).astype(np.float32) * 0.3
            Xu = rng.normal(size=(n, D_CA_RE)).astype(np.float32)
            Xi = rng.normal(size=(n, D_CA_RE)).astype(np.float32)
            users = np.repeat(np.arange(N_CA_USERS), n_per_user)
            items = np.minimum(rng.zipf(1.7, size=n) - 1, N_CA_ITEMS - 1)
            return n, Xg, Xu, Xi, users, items

        w_fe = rng.normal(size=D_CA_FE).astype(np.float32) * 0.2
        w_users = rng.normal(size=(N_CA_USERS, D_CA_RE)).astype(np.float32)
        w_items = rng.normal(size=(N_CA_ITEMS, D_CA_RE)).astype(np.float32)

        def _dataset(n_per_user):
            n, Xg, Xu, Xi, users, items = _rows(n_per_user)
            z = (
                Xg @ w_fe
                + np.einsum("nd,nd->n", Xu, w_users[users])
                + np.einsum("nd,nd->n", Xi, w_items[items])
            )
            y = (rng.random(n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)

            def _coo(X):
                rows, cols = np.nonzero(X)
                return FeatureShard(
                    rows=rows, cols=cols, vals=X[rows, cols], dim=X.shape[1]
                )

            return GameData(
                labels=y,
                feature_shards={
                    "global": _coo(Xg),
                    "per_user": _coo(Xu),
                    "per_item": _coo(Xi),
                },
                id_tags={
                    "userId": np.array([f"u{u:05d}" for u in users]),
                    "itemId": np.array([f"i{i:05d}" for i in items]),
                },
            ), y

        data, _ = _dataset(N_CA_ROWS_PER_USER)
        holdout, y_hold = _dataset(N_CA_HOLD_PER_USER)

        from photon_ml_tpu.opt import AdaptiveSolveConfig

        opt = GlmOptimizationConfiguration(
            regularization=RegularizationContext(RegularizationType.L2),
            regularization_weight=1.0,
            optimizer_config=OptimizerConfig.lbfgs(
                max_iterations=4 if _SMOKE else 12
            ),
            # adaptive driver with chunk_iters >= max_iterations: each
            # bucket finishes in one chunk, so lane compaction never picks
            # data-dependent pow2 widths — the two arms' slightly different
            # trajectories would otherwise visit different widths and break
            # the retrace-parity comparison below with compiles that have
            # nothing to do with the schedule itself
            adaptive=AdaptiveSolveConfig(enabled=True, chunk_iters=16),
        )
        coords = {
            "fixed": FixedEffectCoordinateConfiguration("global", opt),
            "per-user": RandomEffectCoordinateConfiguration(
                feature_shard="per_user",
                data=RandomEffectDataConfiguration(random_effect_type="userId"),
                optimizer=opt,
            ),
            "per-item": RandomEffectCoordinateConfiguration(
                feature_shard="per_item",
                data=RandomEffectDataConfiguration(random_effect_type="itemId"),
                optimizer=opt,
            ),
        }

        emulate = jax.default_backend() == "cpu"
        real_glm, real_re = coord_mod.train_glm, coord_mod.train_random_effects

        def _with_latency(fn):
            # block on the solve's arrays, then (CPU hosts only) sleep the
            # emulated device latency; time.sleep releases the GIL, so in
            # the async arm other coordinates' work proceeds underneath —
            # the same thing real accelerator latency would allow
            def wrapper(*a, **kw):
                out = fn(*a, **kw)
                head = out[0]
                if hasattr(head, "model"):            # GlmFit
                    jax.block_until_ready((head.model, head.result))
                elif hasattr(head, "coefficients"):   # RandomEffectModel
                    jax.block_until_ready(head.coefficients)
                else:
                    jax.block_until_ready(head)
                if emulate:
                    _time.sleep(CA_EMU_LATENCY_S)
                return out
            return wrapper

        # datasets are built ONCE and shared (entity grouping is identical
        # for both schedules and not what this bench measures)
        builder = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinates=coords,
            num_outer_iterations=N_CA_OUTER,
        )
        built = {
            cid: builder._build_coordinate(cid, cfg, data)
            for cid, cfg in builder.coordinate_configs.items()
        }

        coord_mod.train_glm = _with_latency(real_glm)
        coord_mod.train_random_effects = _with_latency(real_re)
        try:
            def _fit(schedule):
                est = GameEstimator(
                    task=TaskType.LOGISTIC_REGRESSION,
                    coordinates=coords,
                    num_outer_iterations=N_CA_OUTER,
                    score_plane="device",
                    schedule=schedule,
                    staleness=CA_STALENESS,
                )
                t0 = _time.perf_counter()
                fit = est._run_fit(built, data, None, None, None)
                return est, fit, _time.perf_counter() - t0

            # warm both arms up front: the sync pass compiles every pow2
            # program, so retrace parity below checks that async added NONE
            _fit("sync")
            traces_sync = solver_trace_counts()
            _fit("async")
            traces_async = solver_trace_counts()
            trace_parity = traces_sync == traces_async

            reps = 1 if _SMOKE else 3
            runs = {}
            for schedule in ("sync", "async"):
                best = None
                for _ in range(reps):
                    est, fit, wall = _fit(schedule)
                    if best is None or wall < best[2]:
                        best = (est, fit, wall)
                runs[schedule] = best
        finally:
            coord_mod.train_glm = real_glm
            coord_mod.train_random_effects = real_re

        est_s, fit_s, wall_s = runs["sync"]
        est_a, fit_a, wall_a = runs["async"]
        auc_sync = _auc(
            np.asarray(fit_s.model.score(holdout), np.float64), y_hold
        )
        auc_async = _auc(
            np.asarray(fit_a.model.score(holdout), np.float64), y_hold
        )

        from photon_ml_tpu.telemetry import get_registry

        get_registry().record_transfer_stats(est_a.last_transfer_stats)
        telemetry = summarize_telemetry()
        # replay the bench's own ledger: the analyzer attributes the async
        # arm's concurrent span time as per-phase overlap_s (the sync arm
        # contributes none), and its coverage proves no double-counting
        report = analyze_ledger(telemetry["ledger"])
        overlap_phases = {
            p: report.phase_overlap(p)
            for p in ("fe_solve", "re_solve", "cd_driver")
        }
        busy_total = sum(
            float(v.get("busy_s", 0.0)) for v in report.phases.values()
        )

        payload = {
            "metric": "cd_async_outer_iter_speedup",
            "value": round(wall_s / wall_a, 4) if wall_a > 0 else None,
            "unit": "x_vs_sync",
            "sync_wall_s": round(wall_s, 6),
            "async_wall_s": round(wall_a, 6),
            "sync_outer_iter_s": round(wall_s / N_CA_OUTER, 6),
            "async_outer_iter_s": round(wall_a / N_CA_OUTER, 6),
            "outer_iterations": N_CA_OUTER,
            "staleness": CA_STALENESS,
            "auc_sync": round(auc_sync, 6),
            "auc_async": round(auc_async, 6),
            "auc_delta": round(auc_async - auc_sync, 6),
            "overlap_s": {k: round(v, 6) for k, v in overlap_phases.items()},
            "overlap_total_s": report.overlap_s,
            # share of all span busy time that ran concurrently with other
            # spans (0 for a fully sequential ledger, bounded below 1)
            "overlap_fraction": (
                round(report.overlap_s / busy_total, 4) if busy_total else None
            ),
            "ledger_coverage": report.coverage,
            "trace_parity": trace_parity,
            "device_latency_emulated": emulate,
            "emulated_latency_s": CA_EMU_LATENCY_S if emulate else None,
            "sync_transfers": est_s.last_transfer_stats.snapshot(),
            "async_transfers": est_a.last_transfer_stats.snapshot(),
            "num_rows": int(data.num_rows),
            "num_coordinates": len(coords),
            "backend": jax.default_backend(),
            "telemetry": telemetry,
        }
        print(json.dumps(payload))
        if not _SMOKE or _env_flag("BENCH_CD_ASYNC_WRITE"):
            with open(_CD_ASYNC_PATH, "w") as f:
                json.dump(payload, f, indent=2)
        _append_history(payload, "cd-async")
    except Exception as e:  # noqa: BLE001 - one JSON line per exit path
        print(json.dumps({
            "metric": "cd_async_outer_iter_speedup",
            "error": f"{type(e).__name__}: {e}",
        }))
        sys.exit(1)


_TUNING_PATH = os.path.join(_REPO, "BENCH_TUNING.json")


def _tuning_bench():
    """Close the telemetry loop on the serving replay: run the default
    serving config under a run ledger, replay that ledger through the
    analyzer, let the tuner propose knob overrides from the evidence, then
    re-run the replay with the tuned config and report the default-vs-tuned
    deltas. The headline is the p99 latency delta (positive = tuned is
    faster); BENCH_TUNING.json records both arms plus the proposal that
    connected them. Emits ONE JSON line; an exception emits an error line
    instead (same contract as the other sub-benches)."""
    import sys

    try:
        import jax

        if _SMOKE:
            jax.config.update("jax_platforms", "cpu")
        from photon_ml_tpu.indexmap import DefaultIndexMap
        from photon_ml_tpu.serving import (
            GameScorer,
            ServingArtifact,
            ServingTable,
            replay_requests,
        )
        from photon_ml_tpu.serving.scorer import ScoreRequest
        from photon_ml_tpu.telemetry import analyze_ledger, get_registry
        from photon_ml_tpu.tuning import ab_candidates, get_knob, propose
        from photon_ml_tpu.types import TaskType

        summarize_telemetry = _bench_telemetry("tuning")
        rng = np.random.default_rng(SEED)
        fe_w = (rng.standard_normal(D_SRV_FE) * 0.1).astype(np.float32)
        re_table = (
            rng.standard_normal((N_SRV_ENT, D_SRV_RE)) * 0.3
        ).astype(np.float32)
        artifact = ServingArtifact(
            task=TaskType.LOGISTIC_REGRESSION,
            tables={
                "fixed": ServingTable(
                    feature_shard="global", random_effect_type=None,
                    weights=fe_w,
                ),
                "per_user": ServingTable(
                    feature_shard="per_user", random_effect_type="userId",
                    weights=re_table,
                    entity_index=DefaultIndexMap(
                        {f"u{i}": i for i in range(N_SRV_ENT)}
                    ),
                ),
            },
            model_name="tuning-bench",
        )
        ent = (rng.zipf(1.3, N_SRV_REQ) - 1) % N_SRV_ENT
        fe_idx = rng.integers(0, D_SRV_FE, (N_SRV_REQ, K_SRV_FE))
        fe_val = rng.standard_normal((N_SRV_REQ, K_SRV_FE)).astype(np.float32)
        re_val = rng.standard_normal((N_SRV_REQ, D_SRV_RE)).astype(np.float32)
        requests = [
            ScoreRequest(
                request_id=f"r{i}",
                features={
                    "global": {
                        int(c): float(v)
                        for c, v in zip(fe_idx[i], fe_val[i])
                    },
                    "per_user": {
                        j: float(re_val[i, j]) for j in range(D_SRV_RE)
                    },
                },
                entity_ids={"userId": f"u{ent[i]}"},
            )
            for i in range(N_SRV_REQ)
        ]

        def _replay(buckets, cache_capacity):
            scorer = GameScorer(
                artifact,
                max_nnz={"global": K_SRV_FE, "per_user": D_SRV_RE},
                cache_capacity=cache_capacity,
            )
            for b in buckets:
                scorer.score_batch(requests[:b], bucket_size=b)
            for cache in scorer.caches.values():
                cache.hits = cache.misses = cache.evictions = cache.cold = 0
            _, snap = replay_requests(
                scorer, requests, bucket_sizes=buckets,
                model_id="tuning-bench",
            )
            snap["xla_compiles"] = scorer.compile_count
            return snap

        bucket_knob = get_knob("serving.bucket_sizes")
        cache_knob = get_knob("serving.cache_capacity")
        default_buckets = tuple(bucket_knob.default)
        default_cache = int(cache_knob.default) if not _SMOKE else SRV_CACHE

        # --- arm A: knob-registry defaults, recorded into the run ledger so
        # the analyzer replay has real evidence to tune from
        default_snap = _replay(default_buckets, default_cache)
        get_registry().record_serving_snapshot(default_snap)
        telemetry = summarize_telemetry()

        # --- analyzer replay -> proposal -> tuned candidate (arm B)
        report = analyze_ledger(telemetry["ledger"])
        proposal = propose(report)
        candidates = ab_candidates(proposal, "serve")
        tuned_cfg = candidates[-1] if len(candidates) > 1 else {}
        tuned_buckets = default_buckets
        if "serving.bucket_sizes" in tuned_cfg:
            tuned_buckets = bucket_knob.parse(tuned_cfg["serving.bucket_sizes"])
        tuned_cache = default_cache
        if "serving.cache_capacity" in tuned_cfg:
            tuned_cache = cache_knob.parse(tuned_cfg["serving.cache_capacity"])
        tuned_snap = _replay(tuned_buckets, tuned_cache)

        def _arm(snap, buckets, cache_capacity):
            return {
                "bucket_sizes": list(buckets),
                "cache_capacity": cache_capacity,
                **{
                    k: snap[k]
                    for k in (
                        "latency_p50_s", "latency_p95_s", "latency_p99_s",
                        "batch_fill_ratio", "cache_hit_rate",
                        "replay_requests_per_s", "xla_compiles",
                    )
                    if k in snap
                },
            }

        d_p99 = float(default_snap.get("latency_p99_s", 0.0))
        t_p99 = float(tuned_snap.get("latency_p99_s", 0.0))
        payload = {
            "metric": "tuning_p99_delta_s",
            "value": round(d_p99 - t_p99, 9),
            "unit": "seconds_default_minus_tuned",
            "default": _arm(default_snap, default_buckets, default_cache),
            "tuned": _arm(tuned_snap, tuned_buckets, tuned_cache),
            "deltas": {
                "latency_p99_s": round(t_p99 - d_p99, 9),
                "requests_per_s": round(
                    float(tuned_snap.get("replay_requests_per_s", 0.0))
                    - float(default_snap.get("replay_requests_per_s", 0.0)),
                    3,
                ),
                "xla_compiles": (
                    int(tuned_snap.get("xla_compiles", 0))
                    - int(default_snap.get("xla_compiles", 0))
                ),
            },
            "proposal": {
                "changed": proposal.changed(),
                "knobs_considered": len(proposal.knobs),
                "candidates": candidates,
            },
            "report_coverage": report.coverage,
            "num_requests": N_SRV_REQ,
            "n_entities": N_SRV_ENT,
            "backend": jax.default_backend(),
            "telemetry": telemetry,
        }
        print(json.dumps(payload))
        if not _SMOKE or _env_flag("BENCH_TUNING_WRITE"):
            with open(_TUNING_PATH, "w") as f:
                json.dump(payload, f, indent=2)
        _append_history(payload, "tuning")
    except Exception as e:  # noqa: BLE001 - one JSON line per exit path
        print(json.dumps({
            "metric": "tuning_p99_delta_s",
            "error": f"{type(e).__name__}: {e}",
        }))
        sys.exit(1)


def main():
    """Every exit path emits one JSON line: an uncaught exception anywhere
    (e.g. the tunnel dying mid-phase with the headline already measured)
    must route through _emit_failure, not a bare traceback."""
    try:
        _main()
    except Exception as e:  # noqa: BLE001 - the failure contract
        _emit_failure(f"{type(e).__name__}: {e}")


def _main():
    import argparse
    import sys

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--engine", default="all", choices=["all", "ell", "benes", "fused"],
        help="restrict the small-dim engine A/B to one engine (recorded "
             "measurements; 'all' A/Bs every engine and keeps the fastest)",
    )
    ap.add_argument(
        "--skip-grid", action="store_true",
        help="skip the 16M-coefficient grid north-star config (the "
             "headline falls back to the small-dim measurement)",
    )
    ap.add_argument(
        "--skip-auc-clock", action="store_true",
        help="skip the wall-clock-to-AUC measurement",
    )
    ap.add_argument(
        "--skip-smalldim", action="store_true",
        help="skip the small-dim FE+RE engine A/B extras",
    )
    ap.add_argument(
        "--serving", action="store_true",
        help="run the online-serving benchmark instead of the training "
             "bench: replay a synthetic request stream through the "
             "microbatcher + hot-entity cache, report p99 latency and "
             "sustained requests/sec, and write BENCH_SERVING.json",
    )
    ap.add_argument(
        "--scenarios", action="store_true",
        help="run the scenario replay harness instead of the training "
             "bench: drive the serving workload through seeded traffic "
             "shapes (steady, diurnal, burst storm, cold-entity flood, "
             "hot-swap under load) with request-plane lifecycle sampling "
             "and SLO tracking; writes one per-stage p50/p99 breakdown, "
             "residency rate and SLO verdict per scenario to "
             "BENCH_SCENARIOS.json",
    )
    ap.add_argument(
        "--incremental", action="store_true",
        help="run the nearline-update benchmark instead of the training "
             "bench: warm-started incremental re-solve, delta publish and "
             "zero-re-jit hot-swap; reports update latency and swap "
             "blackout, and writes BENCH_INCREMENTAL.json",
    )
    ap.add_argument(
        "--re-adaptive", action="store_true",
        help="run the adaptive random-effect solve benchmark instead of the "
             "training bench: chunked rounds + lane compaction vs one-shot "
             "lockstep on a skewed-convergence warm-started workload; "
             "reports wall-clock speedup and lane-iteration savings, and "
             "writes BENCH_RE_ADAPTIVE.json",
    )
    ap.add_argument(
        "--streaming", action="store_true",
        help="run the out-of-core streaming benchmark instead of the "
             "training bench: streamed block-sharded fit vs the in-memory "
             "fit on the same on-disk Avro dataset; reports wall clock, "
             "prefetch hide ratio, peak-RSS delta, held-out AUC parity and "
             "post-warmup retraces, and writes BENCH_STREAMING.json",
    )
    ap.add_argument(
        "--multihost", action="store_true",
        help="run the multi-host cluster benchmark instead of the training "
             "bench: streamed full-batch data-parallel CD across 1/2/4 "
             "emulated worker hosts (subprocess mesh, emulated per-block "
             "device latency); reports throughput scaling, held-out AUC "
             "parity vs single-host, and a killed-host-mid-epoch recovery "
             "drill, and writes BENCH_MULTIHOST.json",
    )
    ap.add_argument(
        "--cd-scores", action="store_true",
        help="run the CD score-plane benchmark instead of the training "
             "bench: device-resident running-total score plane vs the host "
             "numpy plane on a 1-FE + 2-RE fit; reports driver overhead "
             "reduction (wall minus solver time), row-transfer counts and "
             "host/device parity, and writes BENCH_CD_SCORES.json",
    )
    ap.add_argument(
        "--cd-async", action="store_true",
        help="run the CD schedule benchmark instead of the training bench: "
             "bounded-staleness async FE/RE pipelining vs the sync loop on "
             "a skewed logistic GLMix fit; reports outer-iteration speedup, "
             "held-out AUC delta, ledger-attributed overlap and retrace "
             "parity, and writes BENCH_CD_ASYNC.json",
    )
    ap.add_argument(
        "--tuning", action="store_true",
        help="run the auto-tuning benchmark instead of the training bench: "
             "replay the serving workload with default knobs under a run "
             "ledger, feed the ledger through the analyzer + tuner, re-run "
             "with the proposed config, and write the default-vs-tuned "
             "deltas to BENCH_TUNING.json",
    )
    args = ap.parse_args()

    if args.tuning:
        _tuning_bench()
        return
    if args.serving:
        _serving_bench()
        return
    if args.scenarios:
        _scenarios_bench()
        return
    if args.incremental:
        _incremental_bench()
        return
    if args.streaming:
        _streaming_bench()
        return
    if args.multihost:
        _multihost_bench()
        return
    if args.re_adaptive:
        _re_adaptive_bench()
        return
    if args.cd_scores:
        _cd_scores_bench()
        return
    if args.cd_async:
        _cd_async_bench()
        return

    watchdog_s = int(os.environ.get("BENCH_WATCHDOG_S", "2700"))
    _arm_watchdog(watchdog_s)
    # persistent caches: repeat runs (and the driver's end-of-round run)
    # skip the 20-40s-per-program TPU compiles and the host routing prep
    from photon_ml_tpu.utils.cachedir import enable_compilation_cache

    enable_compilation_cache()
    if _SMOKE:
        # CPU smoke run: skip the accelerator preflight and force the CPU
        # backend in-process (the TPU plugin overrides JAX_PLATFORMS)
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        _backend_preflight(
            int(os.environ.get("BENCH_PREFLIGHT_S", "300")), watchdog_s
        )

    pin = _load_pin()
    extras: dict = {}
    if os.environ.get("PHOTON_FUSED_TILE_U"):
        # provenance: the fused kernels' tile-height knob shapes the
        # numbers — record the EFFECTIVE cap (malformed env falls back)
        from photon_ml_tpu.ops.fused_perm import _tile_cap

        extras["tile_cap"] = _tile_cap()
    headline = None  # (value, vs_baseline, workload name)

    # ---- HEADLINE FIRST: the north-star 2^24-coef chip tile ----
    if not args.skip_grid:
        grid_built = None
        for grid_engine in ("fused", "benes"):
            try:
                g_pps, g_iters, g_time, g_val, grid_built = _grid_headline(
                    grid_engine
                )
                extras["grid16m_passes_per_s"] = round(g_pps, 1)
                extras["grid16m_engine"] = grid_engine
                extras["grid16m_dim"] = D_GRID
                extras["grid16m_iterations"] = g_iters
                extras["grid16m_solve_s"] = round(g_time, 4)
                print(
                    f"grid16m ({grid_engine}): {g_pps:.0f} passes/s "
                    f"({g_iters} iters in {g_time:.3f}s)",
                    file=sys.stderr,
                )
                break
            except Exception as e:  # pragma: no cover
                print(f"grid north-star ({grid_engine}) failed: {e}",
                      file=sys.stderr)
        if grid_built is not None:
            # the headline number is on the board the moment it exists
            _PARTIAL.update(
                value=extras["grid16m_passes_per_s"],
                headline_workload="grid_2^24_coef_chip_tile_of_1B_layout",
                **{k: v for k, v in extras.items()},
            )
            # CPU baseline for the headline: pinned + fresh (the pin keeps
            # full precision — rounding belongs to display only)
            grid_eval_fresh = _cpu_grid_eval_time()
            fresh = {"grid_eval_s": grid_eval_fresh}
            pin = _maybe_write_pin(pin, fresh)
            vs_fresh = grid_eval_fresh * g_iters / g_time
            extras["vs_baseline_fresh"] = round(vs_fresh, 2)
            if "grid_eval_s" in pin:
                vs_pinned = float(pin["grid_eval_s"]) * g_iters / g_time
                extras["vs_baseline_pinned"] = round(vs_pinned, 2)
                extras["baseline_pin_host"] = pin.get("host", "")
                vs_best = vs_pinned
            else:
                vs_best = vs_fresh
            headline = (
                extras["grid16m_passes_per_s"], round(vs_best, 2),
                "grid_2^24_coef_chip_tile_of_1B_layout",
            )
            _PARTIAL.update(vs_baseline=headline[1], **{
                k: extras[k] for k in
                ("vs_baseline_fresh", "vs_baseline_pinned",
                 "baseline_pin_host") if k in extras
            })
            if not args.skip_auc_clock:
                try:
                    secs, target, achieved, trace = _grid_auc_clock(
                        grid_built
                    )
                    extras["wallclock_to_auc_s"] = round(secs, 3)
                    extras["auc_target"] = round(target, 4)
                    extras["auc_final"] = round(achieved, 4)
                    extras["auc_trace"] = [
                        [round(t, 3), round(a, 4)] for t, a in trace
                    ]
                    _PARTIAL.update(**{
                        k: extras[k] for k in
                        ("wallclock_to_auc_s", "auc_target", "auc_final")
                    })
                except Exception as e:  # pragma: no cover
                    print(f"auc clock failed: {e}", file=sys.stderr)
            del grid_built  # free the tile before the small-dim phase

    # ---- extras: small-dim FE+RE engine A/B ----
    engine_results = {}
    if not args.skip_smalldim:
        fe_np, fe_data, re_np, re_data = _build()
        passes = tpu_time = fe_iters = re_iters = None
        best_fe_data = None
        if args.engine in ("all", "ell"):
            passes, tpu_time, fe_iters, re_iters, _ = _tpu_run(fe_data, re_data)
            engine_results["ell"] = round(passes / tpu_time, 1)
            best_fe_data = fe_data

        # A/B the permutation-routed sparse engines for the FE hot path
        # against XLA gather/scatter; keep the fastest. Prep (host routing)
        # is one-time and untimed; failures fall back to the best path so far.
        routed = [e for e in ("benes", "fused") if args.engine in ("all", e)]
        fused_final = None   # f32 fused final objective: the bf16 quality anchor
        fused_f32_data = None
        for engine in routed:
            try:
                e_data = _routed_fe_data(fe_np, engine)
                e_passes, e_time, e_fe, e_re, e_res = _tpu_run(e_data, re_data)
                engine_results[engine] = round(e_passes / e_time, 1)
                if engine == "fused":
                    fused_final = float(e_res.value)
                    fused_f32_data = e_data
                print(
                    f"{engine} A/B: {e_passes / e_time:.0f} passes/s",
                    file=sys.stderr,
                )
                if tpu_time is None or e_passes / e_time > passes / tpu_time:
                    passes, tpu_time, fe_iters, re_iters = (
                        e_passes, e_time, e_fe, e_re
                    )
                    best_fe_data = e_data
            except Exception as e:  # pragma: no cover
                print(f"{engine} path failed: {e}", file=sys.stderr)

        # bfloat16 network payload: half the routed stage traffic at one
        # entry rounding. Eligible for the small-dim best ONLY when its
        # SOLUTION evaluates to the same optimum under the EXACT f32
        # objective; relative tolerance 1e-4 — measured agreement is ~1e-5.
        # DEFAULT-OFF on hardware (BENCH_BF16=1 opts in; the batched
        # measurement session sets it): the r4 A/Bs measured it losing at
        # both the small-dim (31.4M vs 33.0M) and grid (8.1M vs 13.0M)
        # workloads — the engines are latency-bound, not bandwidth-bound,
        # so halving traffic does not pay. The machinery stays because the
        # quality gate is the reusable artifact (smoke keeps it
        # regression-tested) and a bandwidth-bound future shape may flip
        # the verdict.
        if (
            fused_final is not None
            and args.engine in ("all", "fused")
            and (_env_flag("BENCH_BF16") or _SMOKE)
        ):
            try:
                b_data = _routed_fe_data(fe_np, "fused_bf16")
                b_passes, b_time, b_fe, b_re, b_res = _tpu_run(b_data, re_data)
                engine_results["fused_bf16"] = round(b_passes / b_time, 1)
                b_val = _f32_objective_value(b_res.w, fused_f32_data)
                quality_ok = (
                    abs(b_val - fused_final) <= 1e-4 * abs(fused_final)
                )
                print(
                    f"fused_bf16 A/B: {b_passes / b_time:.0f} passes/s "
                    f"(f32 objective at bf16 solution {b_val:.6g} vs "
                    f"{fused_final:.6g}, quality_ok={quality_ok})",
                    file=sys.stderr,
                )
                if quality_ok and b_passes / b_time > passes / tpu_time:
                    passes, tpu_time, fe_iters, re_iters = (
                        b_passes, b_time, b_fe, b_re
                    )
                    best_fe_data = b_data
            except Exception as e:  # pragma: no cover
                print(f"fused_bf16 path failed: {e}", file=sys.stderr)

        # A/B the fused pallas kernels (dense RE inner loop) on real TPU
        # over the best FE engine; keep whichever is faster.
        from photon_ml_tpu.ops.pallas_kernels import pallas_available

        if pallas_available() and args.engine == "all" and tpu_time is not None:
            try:
                p_passes, p_time, p_fe, p_re, _ = _tpu_run(
                    best_fe_data, re_data, use_pallas=True
                )
                engine_results["pallas_re"] = round(p_passes / p_time, 1)
                print(
                    f"pallas A/B: best={passes / tpu_time:.0f} "
                    f"pallas={p_passes / p_time:.0f} passes/s",
                    file=sys.stderr,
                )
                if p_passes / p_time > passes / tpu_time:
                    passes, tpu_time, fe_iters, re_iters = (
                        p_passes, p_time, p_fe, p_re
                    )
            except Exception as e:  # pragma: no cover
                print(f"pallas path failed, using XLA: {e}", file=sys.stderr)

        if tpu_time is not None:
            extras["engines"] = engine_results
            extras["smalldim_passes_per_s"] = round(passes / tpu_time, 1)
            fe_fresh, re_fresh = _cpu_smalldim_eval_times(fe_np, re_np)
            fresh = {"fe_eval_s": fe_fresh, "re_eval_s": re_fresh}
            pin = _maybe_write_pin(pin, fresh)
            fe_p = float(pin.get("fe_eval_s", fe_fresh))
            re_p = float(pin.get("re_eval_s", re_fresh))
            cpu_t = fe_p * fe_iters + re_p * re_iters
            extras["smalldim_vs_baseline"] = round(cpu_t / tpu_time, 2)
            _PARTIAL.update(
                engines=dict(engine_results),
                smalldim_passes_per_s=extras["smalldim_passes_per_s"],
                smalldim_vs_baseline=extras["smalldim_vs_baseline"],
            )
            if headline is None:
                # grid skipped or failed: the small-dim number carries the
                # line so the bench still reports a real measurement
                cpu_fresh_t = fe_fresh * fe_iters + re_fresh * re_iters
                extras.setdefault(
                    "vs_baseline_fresh", round(cpu_fresh_t / tpu_time, 2)
                )
                headline = (
                    extras["smalldim_passes_per_s"],
                    extras["smalldim_vs_baseline"],
                    "smalldim_fe_re",
                )
                _PARTIAL.update(
                    value=headline[0], vs_baseline=headline[1],
                    headline_workload="smalldim_fe_re",
                )

    if headline is None:
        _emit_failure("no workload produced a measurement")

    payload = {
        "metric": "glmix_logistic_train_throughput",
        "value": headline[0],
        "unit": "example_passes/sec/chip",
        "vs_baseline": headline[1],
        "headline_workload": headline[2],
        **extras,
    }
    print(json.dumps(payload))
    _write_lastgood(payload)


if __name__ == "__main__":
    main()
