"""Headline benchmark: GLMix logistic training throughput on one chip.

Workload = one GAME coordinate-descent pass of the flagship model (BASELINE
config 4): a fixed-effect L-BFGS solve over sparse (ELL) features, then the
residual-offset per-entity random-effect vmap'd solve. Throughput counts
example-passes (rows touched per objective evaluation) per second.

Two BASELINE.md north-star metrics ride along in the same JSON line:
- ``wallclock_to_auc_s``: MLPerf-style time-to-accuracy — seconds of
  training until held-out AUC is within AUC_MARGIN of the converged final
  AUC of this fixed workload. Unlike passes/sec this cannot be gamed by
  slower-converging configurations.
- ``grid16m_passes_per_s``: throughput of the 2-D (data x feat) grid engine
  at a single-chip-sized shard of the 1B-coefficient layout (2^24 ≈ 16.8M
  feature-sharded coefficients on a 1x1 mesh) — the layout BASELINE.json
  targets at production scale, measured at its per-chip tile size.

``vs_baseline`` is the measured speedup against a CPU/numpy implementation of
the identical math (the reference's per-partition Breeze kernels without any
Spark shuffle/broadcast overhead — a deliberately generous stand-in for the
Spark-CPU baseline, which BASELINE.json targets at >=10x).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
``--engine ell|benes|fused`` restricts the FE engine A/B to one engine (the
recorded-measurement workflow: dev-scripts/tpu_validate_fused.py);
``BENCH_SMOKE=1`` shrinks every shape for a CPU smoke run.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

_SMOKE = bool(int(os.environ.get("BENCH_SMOKE", "0")))

SEED = 0
N_FE = 1 << (12 if _SMOKE else 18)   # fixed-effect rows
K_NNZ = 32          # nonzeros per row
D_FE = 1 << (10 if _SMOKE else 17)   # global feature dim
N_ENT = 256 if _SMOKE else 4096      # random-effect entities
S_ENT = 32          # samples per entity
D_RE = 16           # per-entity projected dim

# North-star grid shard (single-chip tile of the 1B-coef layout)
N_GRID = 1 << (12 if _SMOKE else 20)     # rows
D_GRID = 1 << (12 if _SMOKE else 24)     # feature-sharded coefficients
K_GRID = 16                              # nonzeros per row

AUC_MARGIN = 0.005  # target = generator Bayes AUC - margin (fixed per seed)


def _build():
    import jax.numpy as jnp

    from photon_ml_tpu.data.random_effect import ReBucket
    from photon_ml_tpu.ops.data import LabeledData
    from photon_ml_tpu.ops.features import DenseFeatures, EllFeatures

    rng = np.random.default_rng(SEED)
    ell_vals = rng.standard_normal((N_FE, K_NNZ)).astype(np.float32)
    ell_idx = rng.integers(0, D_FE, (N_FE, K_NNZ)).astype(np.int32)
    w_true = (rng.standard_normal(D_FE) * 0.1).astype(np.float32)
    z = (ell_vals * w_true[ell_idx]).sum(-1)
    y = (rng.random(N_FE) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)

    fe_data = LabeledData.create(
        EllFeatures(values=jnp.asarray(ell_vals), indices=jnp.asarray(ell_idx), num_cols=D_FE),
        jnp.asarray(y),
    )

    # held-out rows from the same generator: the convergence clock's metric
    n_val = N_FE // 4
    val_vals = rng.standard_normal((n_val, K_NNZ)).astype(np.float32)
    val_idx = rng.integers(0, D_FE, (n_val, K_NNZ)).astype(np.int32)
    val_z = (val_vals * w_true[val_idx]).sum(-1)
    val_y = (rng.random(n_val) < 1.0 / (1.0 + np.exp(-val_z))).astype(np.float32)
    fe_val = (val_vals, val_idx, val_y)

    re_x = rng.standard_normal((N_ENT, S_ENT, D_RE)).astype(np.float32)
    re_wtrue = (rng.standard_normal((N_ENT, D_RE)) * 0.3).astype(np.float32)
    re_z = np.einsum("esd,ed->es", re_x, re_wtrue)
    re_y = (rng.random((N_ENT, S_ENT)) < 1.0 / (1.0 + np.exp(-re_z))).astype(np.float32)
    re_bucket = ReBucket(
        X=jnp.asarray(re_x),
        labels=jnp.asarray(re_y),
        offsets=jnp.zeros((N_ENT, S_ENT), dtype=jnp.float32),
        weights=jnp.ones((N_ENT, S_ENT), dtype=jnp.float32),
        sample_pos=jnp.zeros((N_ENT, S_ENT), dtype=jnp.int32),
        proj_indices=jnp.zeros((N_ENT, D_RE), dtype=jnp.int32),
        proj_valid=jnp.ones((N_ENT, D_RE), dtype=bool),
    )
    re_data = LabeledData(
        features=DenseFeatures(matrix=re_bucket.X),
        labels=re_bucket.labels,
        offsets=re_bucket.offsets,
        weights=re_bucket.weights,
        norm=None,
    )
    re_xv = rng.standard_normal((N_ENT, S_ENT, D_RE)).astype(np.float32)
    re_zv = np.einsum("esd,ed->es", re_xv, re_wtrue)
    re_yv = (rng.random((N_ENT, S_ENT)) < 1.0 / (1.0 + np.exp(-re_zv))).astype(np.float32)
    re_val = (re_xv, re_yv)
    return (ell_vals, ell_idx, y), fe_data, (re_x, re_y), re_data, fe_val, re_val


def _auc(scores: np.ndarray, labels: np.ndarray) -> float:
    """Rank-sum ROC AUC (ties averaged), vectorized float64 numpy."""
    order = np.argsort(scores, kind="stable")
    s_sorted = scores[order]
    # average rank of each tie group, assigned back per element
    uniq, inv, counts = np.unique(s_sorted, return_inverse=True, return_counts=True)
    ends = np.cumsum(counts).astype(np.float64)       # 1-based end rank per group
    avg = ends - (counts - 1) / 2.0                   # mean of [end-c+1 .. end]
    ranks = np.empty(len(scores), dtype=np.float64)
    ranks[order] = avg[inv]
    pos = labels > 0.5
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    if not n_pos or not n_neg:
        return float("nan")
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


def _f32_objective_value(w, fe_data_f32) -> float:
    """The exact (f32-engine) FE objective at ``w`` — the quality anchor for
    reduced-precision engines: their own reported objective rides the same
    rounded operator, so a systematic payload bias could hide there."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.losses.objective import make_glm_objective
    from photon_ml_tpu.losses.pointwise import LogisticLoss

    objective = make_glm_objective(LogisticLoss)
    return float(
        jax.jit(objective.value)(w, fe_data_f32, jnp.float32(1.0))
    )


def _settle_dispatch(fn) -> None:
    """Run ``fn`` once more and host-fetch its result leaves.

    On the remote backend, jax.block_until_ready can return prematurely on
    the FIRST dispatch after a compile-cache load (measured: 0.2 ms "ready"
    while the execution takes seconds, completing during a later fetch).
    Fetching the warm-up result does NOT clear that state — it is the next
    dispatch whose completion signal is broken — so the barrier must be a
    fresh dispatch force-fetched to host. Call after the compile warm-up,
    before trusting any block_until_ready-based timer.
    """
    import jax

    for x in jax.tree.leaves(fn()):
        np.asarray(x)


def _wallclock_to_auc(fe_data, re_data, fe_val, re_val):
    """MLPerf-style time-to-accuracy on held-out data: run warm-started CD
    passes, record (elapsed, AUC) after each, and report the first elapsed
    time at which AUC is within AUC_MARGIN of the converged final AUC.
    Returns (seconds, target_auc, final_auc). The workload and margin are
    fixed by the bench, so a slower-converging configuration cannot score
    better by iterating less (BASELINE.md north-star metric)."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.losses.objective import make_glm_objective
    from photon_ml_tpu.losses.pointwise import LogisticLoss
    from photon_ml_tpu.opt.config import (
        GlmOptimizationConfiguration,
        OptimizerConfig,
        RegularizationContext,
    )
    from photon_ml_tpu.opt.solve import solve
    from photon_ml_tpu.types import RegularizationType

    val_vals, val_idx, val_y = fe_val
    re_xv, re_yv = re_val

    objective = make_glm_objective(LogisticLoss)
    cfg = GlmOptimizationConfiguration(
        optimizer_config=OptimizerConfig.lbfgs(max_iterations=10),
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )
    fe_solver = jax.jit(lambda w0, dd: solve(objective, w0, dd, cfg))
    re_solver = jax.jit(
        jax.vmap(lambda w0, dd: solve(objective, w0, dd, cfg), in_axes=(0, 0))
    )
    # warm up compiles outside the timed region (the reference's JVM warmup
    # is likewise excluded by its integ-test harness)
    w_fe = jnp.zeros((D_FE,), dtype=jnp.float32)
    w_re = jnp.zeros((N_ENT, D_RE), dtype=jnp.float32)
    jax.block_until_ready(fe_solver(w_fe, fe_data).w)
    jax.block_until_ready(re_solver(w_re, re_data).w)
    _settle_dispatch(lambda: fe_solver(w_fe, fe_data).w)
    _settle_dispatch(lambda: re_solver(w_re, re_data).w)

    trace = []  # (training elapsed_s, auc) per CD pass
    trained = 0.0  # training-only clock: host-side AUC evaluation excluded
    for _ in range(8):  # warm-started CD passes, to convergence
        t0 = time.perf_counter()
        w_fe = fe_solver(w_fe, fe_data).w
        w_re = re_solver(w_re, re_data).w
        jax.block_until_ready((w_fe, w_re))
        trained += time.perf_counter() - t0
        wf, wr = np.asarray(w_fe), np.asarray(w_re)
        fe_scores = (val_vals * wf[val_idx]).sum(-1)
        re_scores = np.einsum("esd,ed->es", re_xv, wr)
        auc = 0.5 * (
            _auc(fe_scores, val_y) + _auc(re_scores.ravel(), re_yv.ravel())
        )
        trace.append((trained, auc))
        if len(trace) >= 2 and abs(trace[-1][1] - trace[-2][1]) < 1e-4:
            break  # converged
    final = max(a for _, a in trace)
    target = final - AUC_MARGIN
    secs = next(t for t, a in trace if a >= target)
    return secs, target, final


def _grid_northstar(engine: str = "benes", payload_dtype: str = "float32"):
    """Single-chip shard of the 1B-coef layout: N_GRID rows x D_GRID
    feature-sharded coefficients through parallel/grid_features on a 1x1
    mesh (the per-chip tile of the production data x feat grid). Returns
    (passes/sec, final objective) over an L-BFGS solve."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.losses.objective import make_glm_objective
    from photon_ml_tpu.losses.pointwise import LogisticLoss
    from photon_ml_tpu.ops.data import LabeledData
    from photon_ml_tpu.opt.config import (
        GlmOptimizationConfiguration,
        OptimizerConfig,
        RegularizationContext,
    )
    from photon_ml_tpu.opt.solve import solve
    from photon_ml_tpu.parallel.grid_features import (
        grid_from_coo,
        grid_mesh,
        shard_vector_data,
        shard_vector_feat,
    )
    from photon_ml_tpu.types import RegularizationType

    rng = np.random.default_rng(SEED + 1)
    rows = np.repeat(np.arange(N_GRID, dtype=np.int64), K_GRID)
    cols = rng.integers(0, D_GRID, N_GRID * K_GRID).astype(np.int64)
    vals = rng.standard_normal(N_GRID * K_GRID).astype(np.float32)
    # labels from a sparse true model (materializing w_true [D_GRID] is fine:
    # one float per coefficient, same as the solve itself)
    w_true = (rng.standard_normal(D_GRID) * 0.1).astype(np.float32)
    z = (vals * w_true[cols]).reshape(N_GRID, K_GRID).sum(-1)
    y = (rng.random(N_GRID) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)

    mesh = grid_mesh(1, 1)
    gf = grid_from_coo(
        rows, cols, vals, (N_GRID, D_GRID), mesh, engine=engine,
        plan_cache=_plan_cache_dir(), payload_dtype=payload_dtype,
    )
    y_pad = np.zeros(gf.num_rows, np.float32)
    y_pad[:N_GRID] = y
    wt_pad = np.zeros(gf.num_rows, np.float32)
    wt_pad[:N_GRID] = 1.0
    data = LabeledData.create(
        gf,
        shard_vector_data(jnp.asarray(y_pad), mesh),
        weights=shard_vector_data(jnp.asarray(wt_pad), mesh),
    )
    objective = make_glm_objective(LogisticLoss)
    cfg = GlmOptimizationConfiguration(
        optimizer_config=OptimizerConfig.lbfgs(max_iterations=10),
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )
    solver = jax.jit(lambda w0, dd: solve(objective, w0, dd, cfg))
    w0 = shard_vector_feat(jnp.zeros(gf.dim, jnp.float32), mesh)
    res = solver(w0, data)
    jax.block_until_ready(res.w)  # compile warm-up
    _settle_dispatch(lambda: solver(w0, data).w)
    best = np.inf
    for _ in range(2):
        t0 = time.perf_counter()
        res = solver(w0, data)
        jax.block_until_ready(res.w)
        best = min(best, time.perf_counter() - t0)
    iters = int(res.iterations)
    return N_GRID * max(iters, 1) / best, float(res.value)


def _plan_cache_dir():
    """Routing-plan cache location: BENCH_PLAN_CACHE when set ("" disables),
    else None = the library's safe per-uid default (sparse_perm
    default_plan_cache), shared with the CLIs across runs."""
    return os.environ.get("BENCH_PLAN_CACHE")


def _routed_fe_data(fe_np, engine: str):
    """The same fixed-effect problem through a permutation-routed sparse
    engine: ``"benes"`` = stage-by-stage (ops/sparse_perm.py), ``"fused"`` =
    2m+1 fused kernels per linear map (ops/fused_perm.py), ``"fused_bf16"``
    = fused with bfloat16 network payload (half the stage traffic; entry
    rounding only). The one-time host routing prep is excluded from the
    timed region, like the reference's RDD dataset build; plans are
    pattern-keyed and cached across runs."""
    import functools

    import jax.numpy as jnp

    from photon_ml_tpu.ops.data import LabeledData
    from photon_ml_tpu.ops import fused_perm, sparse_perm

    ell_vals, ell_idx, y = fe_np
    rows = np.repeat(np.arange(N_FE, dtype=np.int64), K_NNZ)
    builder = {
        "benes": sparse_perm.from_coo,
        "fused": fused_perm.from_coo,
        "fused_bf16": functools.partial(
            fused_perm.from_coo, payload_dtype="bfloat16"
        ),
    }[engine]
    feats = builder(rows, ell_idx.ravel().astype(np.int64), ell_vals.ravel(),
                    (N_FE, D_FE), plan_cache=_plan_cache_dir())
    return LabeledData.create(feats, jnp.asarray(y))


def _tpu_run(fe_data, re_data, use_pallas: bool = False):
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.losses.objective import make_glm_objective
    from photon_ml_tpu.losses.pointwise import LogisticLoss
    from photon_ml_tpu.opt.config import GlmOptimizationConfiguration, OptimizerConfig
    from photon_ml_tpu.opt.solve import solve

    objective = make_glm_objective(LogisticLoss, use_pallas=use_pallas)
    cfg = GlmOptimizationConfiguration(
        optimizer_config=OptimizerConfig.lbfgs(max_iterations=50),
        regularization_weight=1.0,
    )
    l2 = jnp.float32(1.0)

    fe_solver = jax.jit(lambda w0, dd: solve(objective, w0, dd, cfg, l2_weight=l2))
    re_solver = jax.jit(
        jax.vmap(lambda w0, dd: solve(objective, w0, dd, cfg, l2_weight=l2), in_axes=(0, 0))
    )
    w0_fe = jnp.zeros((D_FE,), dtype=jnp.float32)
    w0_re = jnp.zeros((N_ENT, D_RE), dtype=jnp.float32)

    def one_pass():
        fe_res = fe_solver(w0_fe, fe_data)
        re_res = re_solver(w0_re, re_data)
        jax.block_until_ready((fe_res.w, re_res.w))
        return fe_res, re_res

    fe_res, re_res = one_pass()  # compile warm-up
    _settle_dispatch(lambda: [r.w for r in one_pass()])
    best = np.inf
    for _ in range(3):
        t0 = time.perf_counter()
        fe_res, re_res = one_pass()
        best = min(best, time.perf_counter() - t0)

    fe_iters = int(fe_res.iterations)
    re_iters = float(jnp.mean(re_res.iterations))
    # rows touched per objective evaluation x evaluations (1 eval/iter is a
    # lower bound; line-search extras are free upside not counted)
    passes = N_FE * fe_iters + N_ENT * S_ENT * re_iters
    return passes, best, fe_iters, re_iters, fe_res


def _cpu_baseline(fe_np, re_np, fe_iters, re_iters):
    """Same math in numpy: the reference's Breeze per-partition kernels
    (ValueAndGradientAggregator) with zero communication cost."""
    ell_vals, ell_idx, y = fe_np
    w = np.zeros(D_FE, dtype=np.float32)

    def fe_eval():
        z = (ell_vals * w[ell_idx]).sum(-1)
        p = 1.0 / (1.0 + np.exp(-z))
        c = (p - y).astype(np.float32)
        g = np.zeros(D_FE, dtype=np.float32)
        np.add.at(g, ell_idx.ravel(), (ell_vals * c[:, None]).ravel())
        return g

    n_time = 3
    t0 = time.perf_counter()
    for _ in range(n_time):
        fe_eval()
    fe_per_eval = (time.perf_counter() - t0) / n_time

    re_x, re_y = re_np
    wr = np.zeros((N_ENT, D_RE), dtype=np.float32)

    def re_eval():
        z = np.einsum("esd,ed->es", re_x, wr)
        p = 1.0 / (1.0 + np.exp(-z))
        c = p - re_y
        return np.einsum("esd,es->ed", re_x, c)

    t0 = time.perf_counter()
    for _ in range(n_time):
        re_eval()
    re_per_eval = (time.perf_counter() - t0) / n_time

    return fe_per_eval * fe_iters + re_per_eval * re_iters


# Best result measured so far: the watchdog emits THIS (with the error
# attached) instead of a zero line when a later phase hangs — a wedged
# tunnel after the headline measurement must not discard it.
_PARTIAL: dict = {}


def _emit_failure(error: str) -> None:
    """The benchmark's machine-read failure contract: one well-formed JSON
    line (the best partial result if any phase completed, else zeros),
    then a nonzero exit."""
    import os
    import sys

    payload = {
        "metric": "glmix_logistic_train_throughput",
        "value": 0.0,
        "unit": "example_passes/sec/chip",
        "vs_baseline": 0.0,
    }
    try:
        # the watchdog thread may race a main-thread _PARTIAL.update (and
        # nested dicts may be live references); any serialization failure
        # must still produce the zeros line, never a hang
        snap = json.loads(json.dumps(dict(_PARTIAL), default=str))
        payload.update(snap)
    except Exception:
        pass
    payload["error"] = error
    try:
        line = json.dumps(payload)
    except Exception:
        line = json.dumps(
            {"metric": "glmix_logistic_train_throughput", "value": 0.0,
             "unit": "example_passes/sec/chip", "vs_baseline": 0.0,
             "error": error}
        )
    print(line, flush=True)
    sys.stderr.write(f"bench failure: {error}\n")
    os._exit(2 if not payload.get("value") else 3)


def _arm_watchdog(seconds: int = 2700) -> None:
    """Hard deadline: if the accelerator backend hangs (e.g. the device
    tunnel is wedged), still emit one well-formed JSON line and exit instead
    of blocking the caller forever."""
    import threading

    t = threading.Timer(
        seconds, lambda: _emit_failure(f"watchdog: no result within {seconds}s")
    )
    t.daemon = True
    t.start()


def _backend_preflight(timeout_s: int = 300, watchdog_s: int = 2700) -> None:
    """Prove the accelerator backend answers at all before building the
    workload: a wedged device tunnel hangs on first use, and failing in
    minutes beats burning the full watchdog budget. Timeouts (a flapping
    tunnel) retry while they fit in 40% of the watchdog budget; a child
    that exits with an error (deterministic breakage) fails immediately
    with its stderr tail."""
    import subprocess
    import sys
    import time as _time

    code = "import jax, jax.numpy as jnp; jax.block_until_ready(jnp.arange(4).sum())"
    budget = max(int(0.4 * watchdog_s), timeout_s)
    attempts = max(1, min(3, (budget + 60) // (timeout_s + 60)))
    last = "unknown"
    for attempt in range(attempts):
        try:
            subprocess.run(
                [sys.executable, "-c", code], timeout=timeout_s,
                check=True, capture_output=True,
            )
            return
        except subprocess.CalledProcessError as e:
            tail = (e.stderr or b"")[-300:].decode("utf-8", "replace").strip()
            _emit_failure(f"backend preflight child failed: {tail or e}")
        except Exception as e:
            last = type(e).__name__
            print(
                f"backend preflight attempt {attempt + 1}/{attempts} "
                f"failed: {last}",
                file=sys.stderr,
            )
            if attempt + 1 < attempts:
                _time.sleep(60)
    _emit_failure(f"backend preflight failed after {attempts} attempts: {last}")


def main():
    import argparse
    import sys

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--engine", default="all", choices=["all", "ell", "benes", "fused"],
        help="restrict the FE engine A/B to one engine (recorded "
             "measurements; 'all' A/Bs every engine and keeps the fastest)",
    )
    ap.add_argument(
        "--skip-grid", action="store_true",
        help="skip the 16M-coefficient grid north-star config",
    )
    ap.add_argument(
        "--skip-auc-clock", action="store_true",
        help="skip the wall-clock-to-AUC measurement",
    )
    args = ap.parse_args()

    watchdog_s = int(os.environ.get("BENCH_WATCHDOG_S", "2700"))
    _arm_watchdog(watchdog_s)
    # persistent caches: repeat runs (and the driver's end-of-round run)
    # skip the 20-40s-per-program TPU compiles and the host routing prep
    from photon_ml_tpu.utils.cachedir import enable_compilation_cache

    enable_compilation_cache()
    if _SMOKE:
        # CPU smoke run: skip the accelerator preflight and force the CPU
        # backend in-process (the TPU plugin overrides JAX_PLATFORMS)
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        _backend_preflight(
            int(os.environ.get("BENCH_PREFLIGHT_S", "300")), watchdog_s
        )
    fe_np, fe_data, re_np, re_data, fe_val, re_val = _build()
    engine_results = {}
    def _record_extras(extras_map):
        _PARTIAL.update(
            {k: dict(v) if isinstance(v, dict) else v
             for k, v in extras_map.items()}
        )

    if args.engine in ("all", "ell"):
        passes, tpu_time, fe_iters, re_iters, _ = _tpu_run(fe_data, re_data)
        engine_results["ell"] = round(passes / tpu_time, 1)
        best_fe_data = fe_data
        _PARTIAL.update(
            value=round(passes / tpu_time, 1), engines=dict(engine_results)
        )
    else:
        passes, tpu_time, fe_iters, re_iters = None, None, None, None
        best_fe_data = None

    # A/B the permutation-routed sparse engines for the FE hot path against
    # XLA gather/scatter; keep the fastest. Prep (host routing) is one-time
    # and untimed; failures fall back silently to the best path so far.
    routed = [e for e in ("benes", "fused") if args.engine in ("all", e)]
    fused_final = None   # f32 fused final objective: the bf16 quality anchor
    fused_f32_data = None
    for engine in routed:
        try:
            e_data = _routed_fe_data(fe_np, engine)
            e_passes, e_time, e_fe, e_re, e_res = _tpu_run(e_data, re_data)
            engine_results[engine] = round(e_passes / e_time, 1)
            if engine == "fused":
                fused_final = float(e_res.value)
                fused_f32_data = e_data
            print(
                f"{engine} A/B: {e_passes / e_time:.0f} passes/s",
                file=sys.stderr,
            )
            if tpu_time is None or e_passes / e_time > passes / tpu_time:
                passes, tpu_time, fe_iters, re_iters = e_passes, e_time, e_fe, e_re
                best_fe_data = e_data
            _PARTIAL.update(
                value=round(passes / tpu_time, 1), engines=dict(engine_results)
            )
        except Exception as e:  # pragma: no cover
            print(f"{engine} path failed: {e}", file=sys.stderr)
    if tpu_time is None:
        _emit_failure(f"engine {args.engine} produced no measurement")

    # bfloat16 network payload: half the routed stage traffic at one entry
    # rounding. Eligible for the headline ONLY when its SOLUTION evaluates
    # to the same optimum under the EXACT f32 objective (its own reported
    # value rides the rounded operator and could hide a systematic bias);
    # relative tolerance 1e-4 — measured agreement is ~1e-5. Always recorded.
    if fused_final is not None and args.engine in ("all", "fused"):
        try:
            b_data = _routed_fe_data(fe_np, "fused_bf16")
            b_passes, b_time, b_fe, b_re, b_res = _tpu_run(b_data, re_data)
            engine_results["fused_bf16"] = round(b_passes / b_time, 1)
            b_val = _f32_objective_value(b_res.w, fused_f32_data)
            quality_ok = (
                abs(b_val - fused_final) <= 1e-4 * abs(fused_final)
            )
            print(
                f"fused_bf16 A/B: {b_passes / b_time:.0f} passes/s "
                f"(f32 objective at bf16 solution {b_val:.6g} vs "
                f"{fused_final:.6g}, quality_ok={quality_ok})",
                file=sys.stderr,
            )
            if quality_ok and b_passes / b_time > passes / tpu_time:
                passes, tpu_time, fe_iters, re_iters = (
                    b_passes, b_time, b_fe, b_re
                )
                best_fe_data = b_data
            _PARTIAL.update(
                value=round(passes / tpu_time, 1), engines=dict(engine_results)
            )
        except Exception as e:  # pragma: no cover
            print(f"fused_bf16 path failed: {e}", file=sys.stderr)

    # A/B the fused pallas kernels (dense RE inner loop) on real TPU over the
    # best FE engine; keep whichever is faster. Pallas failures fall back.
    from photon_ml_tpu.ops.pallas_kernels import pallas_available

    if pallas_available() and args.engine == "all":
        try:
            p_passes, p_time, p_fe, p_re, _ = _tpu_run(
                best_fe_data, re_data, use_pallas=True
            )
            engine_results["pallas_re"] = round(p_passes / p_time, 1)
            print(
                f"pallas A/B: best={passes / tpu_time:.0f} "
                f"pallas={p_passes / p_time:.0f} passes/s",
                file=sys.stderr,
            )
            if p_passes / p_time > passes / tpu_time:
                passes, tpu_time, fe_iters, re_iters = p_passes, p_time, p_fe, p_re
            _PARTIAL.update(
                value=round(passes / tpu_time, 1), engines=dict(engine_results)
            )
        except Exception as e:  # pragma: no cover
            print(f"pallas path failed, using XLA: {e}", file=sys.stderr)

    # CPU baseline (vs_baseline) BEFORE the long-running extras: a watchdog
    # firing in a later phase must not cost the headline ratio
    cpu_time = _cpu_baseline(fe_np, re_np, fe_iters, re_iters)
    _PARTIAL.update(vs_baseline=round(cpu_time / tpu_time, 2))

    extras = {"engines": engine_results}
    if not args.skip_auc_clock:
        try:
            secs, target, achieved = _wallclock_to_auc(
                best_fe_data, re_data, fe_val, re_val
            )
            extras["wallclock_to_auc_s"] = round(secs, 3)
            extras["auc_target"] = round(target, 4)
            extras["auc_final"] = round(achieved, 4)
            _record_extras(extras)
        except Exception as e:  # pragma: no cover
            print(f"auc clock failed: {e}", file=sys.stderr)
    if not args.skip_grid:
        if args.engine == "all":
            # proxy choice: fastest measured FE engine that the grid
            # supports (shapes differ, but beats hardcoding); benes is
            # retried as a fallback so the metric survives an engine that
            # wins at FE shapes but fails at grid shapes
            candidates = {
                k: v for k, v in engine_results.items()
                if k in ("ell", "benes", "fused")
            }
            grid_engines = (
                [max(candidates, key=candidates.get)] if candidates else []
            )
            if "benes" not in grid_engines:
                grid_engines.append("benes")
        else:
            grid_engines = [args.engine]
        try:
            grid_bf16 = bool(int(os.environ.get("BENCH_GRID_BF16", "0")))
        except ValueError:
            print("ignoring malformed BENCH_GRID_BF16 (want 0/1)", file=sys.stderr)
            grid_bf16 = False
        for grid_engine in grid_engines:
            try:
                g_pps, g_val = _grid_northstar(grid_engine)
                extras["grid16m_passes_per_s"] = round(g_pps, 1)
                extras["grid16m_engine"] = grid_engine
                extras["grid16m_dim"] = D_GRID
                _record_extras(extras)
                if grid_engine == "fused" and grid_bf16:
                    # bf16 payload at the grid: RECORD-ONLY (never takes the
                    # metric — the grid gate would compare objectives through
                    # the rounded operator itself, and the measured number
                    # lost anyway: 8.1M vs 13.0M passes/s, the grid blocks
                    # being dispatch-bound, not bandwidth-bound). Opt-in via
                    # BENCH_GRID_BF16=1; its cold compile would otherwise
                    # risk the recorded run's watchdog.
                    try:
                        b_pps, b_val = _grid_northstar(
                            "fused", payload_dtype="bfloat16"
                        )
                        extras["grid16m_fused_bf16_passes_per_s"] = round(
                            b_pps, 1
                        )
                        print(
                            f"grid16m bf16 (record-only): {b_pps:.0f} vs "
                            f"{g_pps:.0f} passes/s "
                            f"(final {b_val:.6g} vs {g_val:.6g})",
                            file=sys.stderr,
                        )
                        _record_extras(extras)
                    except Exception as e:  # pragma: no cover
                        print(f"grid bf16 failed: {e}", file=sys.stderr)
                break
            except Exception as e:  # pragma: no cover
                print(f"grid north-star ({grid_engine}) failed: {e}", file=sys.stderr)

    value = passes / tpu_time
    print(
        json.dumps(
            {
                "metric": "glmix_logistic_train_throughput",
                "value": round(value, 1),
                "unit": "example_passes/sec/chip",
                "vs_baseline": round(cpu_time / tpu_time, 2),
                **extras,
            }
        )
    )


if __name__ == "__main__":
    main()
